"""TPU device backend: PQL bitmap calls on dense HBM blocks.

Execution model (the part that makes this TPU-first rather than a port):

- Per (index, field, view) the backend keeps a STACKED device block
  uint32[n_shards, rows, WORDS] cached in HBM, rebuilt only when a
  fragment version changes (the write path stays host-roaring).
- A query's call tree is compiled ONCE per tree-shape into a single
  jitted function: Row leaves become dynamic row-gathers from the stacked
  blocks (row ids are traced scalars, so consecutive queries with
  different rows reuse the compiled program), bitmap verbs are fused
  bitwise ops over [S, W] slabs, and Count/TopN reduce on device. One
  dispatch + one small transfer per query — essential when the chip is
  reached over a relay where every dispatch costs a round trip.
- The reference's per-shard mapReduce loop (executor.go:2460) therefore
  disappears into XLA: the shard axis is just the leading array dim
  (single chip) or the mesh axis (multi-chip, pilosa_tpu/parallel).

TopN is *exact* on this backend: popcount of every row is one fused
kernel, so the reference's approximate rank-cache candidates + 2-pass
recount (executor.go:860) collapses into one exact pass (SURVEY.md §3.4).

BSI comparison scans and time-quantum unions currently delegate to the
CPU oracle — correct first; device lowering is a later round.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from pilosa_tpu.core.cache import Pair
from pilosa_tpu.core.row import Row
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.exec.cpu import CPUBackend, QueryError
from pilosa_tpu.ops.blocks import WORDS_PER_SHARD, _padded_rows, pack_fragment, unpack_row
from pilosa_tpu.pql.ast import Call, Condition
from pilosa_tpu.roaring import Bitmap

_DEVICE_LOWERED = ("Row", "Union", "Intersect", "Difference", "Xor", "Not", "All")

# Per-(shard,row) popcounts are ≤2^20, so an on-device uint32 reduction over
# the shard axis is exact up to 4095 shards (4096·2^20 = 2^32). Beyond that
# the programs return per-shard partials and the host sums in Python ints.
MAX_DEVICE_SUM_SHARDS = 4095


class _StackedBlocks:
    """Device cache: (index, field, shards) -> uint32[S, R, W] + freshness."""

    def __init__(self, device=None):
        self.device = device
        self._entries: dict[tuple, tuple[tuple, object, int]] = {}

    def get(self, index: str, field_obj, shards: tuple[int, ...]):
        """Returns (block [S,R,W], rows_p). Missing fragments pack as zeros."""
        v = field_obj.view(VIEW_STANDARD)
        frags = {s: (v.fragment(s) if v is not None else None) for s in shards}
        n_rows = max(
            [fr.max_row_id + 1 for fr in frags.values() if fr is not None] or [1]
        )
        rows_p = _padded_rows(n_rows)
        # Freshness via the fragment's process-unique uid + version (id()
        # could be reused by a new object after GC and serve stale blocks).
        fingerprint = tuple(
            (s, (fr.uid, fr.version) if fr is not None else None)
            for s, fr in frags.items()
        ) + (rows_p,)
        # Keyed by (index, field) only: a changed shard set REPLACES the
        # cached stack rather than accumulating per-subset copies in HBM.
        key = (index, field_obj.name)
        cached = self._entries.get(key)
        if cached is not None and cached[0] == fingerprint:
            return cached[1], cached[2]
        host = np.zeros((len(shards), rows_p, WORDS_PER_SHARD), dtype=np.uint32)
        for i, s in enumerate(shards):
            fr = frags[s]
            if fr is not None:
                host[i] = pack_fragment(fr, n_rows=rows_p)
        arr = jax.device_put(host, self.device)
        self._entries[key] = (fingerprint, arr, rows_p)
        return arr, rows_p

    def resident_bytes(self) -> int:
        return sum(int(np.prod(e[1].shape)) * 4 for e in self._entries.values())

    def clear(self) -> None:
        self._entries.clear()


def _tree_key(c: Call):
    """Canonical structural key for a call tree; Row leaves keyed by field
    so one compiled program serves any row ids of that field."""
    if c.name == "Row":
        return ("R", c.field_arg())
    if c.name == "All":
        return ("A",)
    if c.name == "Not":
        return ("N", _tree_key(c.children[0]))
    return (c.name[0], tuple(_tree_key(ch) for ch in c.children))


def _spec_needs_existence(spec) -> bool:
    if spec[0] in ("A", "N"):
        return True
    if spec[0] in ("U", "I", "D", "X"):
        return any(_spec_needs_existence(ch) for ch in spec[1])
    return False


def _eval_spec(spec, blocks_it, rows_it, exist_slab, batched=False):
    """Trace-time recursive evaluation of a tree spec.

    Unbatched: row scalars, result [S, W]. Batched: row vectors [Q],
    result [S, Q, W] — Q same-shape queries fused into one program (the
    serving-style batching that amortizes dispatch+readback round trips).
    """
    tag = spec[0]
    if tag == "R":
        block = next(blocks_it)  # [S, R, W]
        row = next(rows_it)  # scalar or [Q]
        mask = next(rows_it)
        slab = jnp.take(block, row, axis=1)  # [S, W] or [S, Q, W]
        if batched:
            return slab * mask[None, :, None]
        return slab * mask  # mask=0 zeroes rows beyond the packed range
    if tag == "A":
        return exist_slab[:, None, :] if batched else exist_slab
    if tag == "N":
        inner = _eval_spec(spec[1], blocks_it, rows_it, exist_slab, batched)
        ex = exist_slab[:, None, :] if batched else exist_slab
        return ex & ~inner
    children = spec[1]
    acc = _eval_spec(children[0], blocks_it, rows_it, exist_slab, batched)
    for ch in children[1:]:
        v = _eval_spec(ch, blocks_it, rows_it, exist_slab, batched)
        if tag == "U":
            acc = acc | v
        elif tag == "I":
            acc = acc & v
        elif tag == "D":
            acc = acc & ~v
        elif tag == "X":
            acc = acc ^ v
    return acc


class TPUBackend:
    """Drop-in replacement for CPUBackend with device execution.

    Anything not device-lowered falls back to the CPU oracle — results are
    identical (differentially tested in tests/test_tpu.py).
    """

    def __init__(self, holder, device=None):
        self.holder = holder
        self.cpu = CPUBackend(holder)
        self.blocks = _StackedBlocks(device)
        self._fns: dict = {}

    # -- support checks ----------------------------------------------------

    def _device_supported(self, c: Call) -> bool:
        if c.name not in _DEVICE_LOWERED:
            return False
        if c.name == "Row":
            if any(isinstance(v, Condition) for v in c.args.values()):
                return False
            if "from" in c.args or "to" in c.args:
                return False
            try:
                c.field_arg()
            except ValueError:
                return False
            return True
        if c.name in ("Union", "Intersect", "Difference", "Xor") and not c.children:
            return False  # CPU path produces the reference error/empty result
        if c.name == "Not" and len(c.children) != 1:
            return False  # CPU path raises the reference arity error
        return all(self._device_supported(ch) for ch in c.children)

    # -- assembly ----------------------------------------------------------

    def _collect_leaves(self, index: str, c: Call, shards: tuple[int, ...],
                        blocks: list, rows: list) -> None:
        """Depth-first leaf collection matching _eval_spec's iteration order."""
        if c.name == "Row":
            field_name = c.field_arg()
            row_id, ok = c.uint64_arg(field_name)
            if not ok:
                raise QueryError("Row() must specify row")
            idx = self.holder.index(index)
            f = idx.field(field_name) if idx else None
            if f is None:
                raise QueryError(f"field not found: {field_name}")
            block, rows_p = self.blocks.get(index, f, shards)
            blocks.append(block)
            rows.append(np.uint32(min(row_id, rows_p - 1)))
            rows.append(np.uint32(1 if row_id < rows_p else 0))
            return
        for ch in c.children:
            self._collect_leaves(index, ch, shards, blocks, rows)

    def _existence_block(self, index: str, shards: tuple[int, ...]):
        idx = self.holder.index(index)
        ef = idx.existence_field() if idx else None
        if ef is None:
            raise QueryError(f"index does not support existence tracking: {index}")
        block, _ = self.blocks.get(index, ef, shards)
        return block

    def _assemble(self, index: str, c: Call, shards: tuple[int, ...], spec):
        blocks: list = []
        rows: list = []
        self._collect_leaves(index, c, shards, blocks, rows)
        if _spec_needs_existence(spec):
            exist = self._existence_block(index, shards)
        else:
            exist = None
        return tuple(blocks), tuple(rows), exist

    # -- compiled programs -------------------------------------------------

    def _program(self, kind: str, spec, with_exist: bool):
        """One jitted program per (kind, tree-shape, existence-presence)."""
        key = (kind, spec, with_exist)
        fn = self._fns.get(key)
        if fn is not None:
            return fn

        if kind == "count":

            @jax.jit
            def fn(blocks, rows, exist_block):
                exist_slab = (
                    exist_block[:, 0, :] if exist_block is not None else None
                )
                slab = _eval_spec(spec, iter(blocks), iter(rows), exist_slab)
                per_shard = jnp.sum(
                    jax.lax.population_count(slab), axis=-1, dtype=jnp.uint32
                )
                # Shape is static at trace time: scalar-reduce on device
                # only while the uint32 sum is exact; else return [S]
                # partials for an exact host sum.
                if per_shard.shape[0] <= MAX_DEVICE_SUM_SHARDS:
                    return jnp.sum(per_shard, dtype=jnp.uint32)
                return per_shard

        elif kind == "vec":

            @jax.jit
            def fn(blocks, rows, exist_block):
                exist_slab = (
                    exist_block[:, 0, :] if exist_block is not None else None
                )
                return _eval_spec(spec, iter(blocks), iter(rows), exist_slab)

        elif kind == "topn_src":

            @jax.jit
            def fn(field_block, blocks, rows, exist_block):
                exist_slab = (
                    exist_block[:, 0, :] if exist_block is not None else None
                )
                src = _eval_spec(spec, iter(blocks), iter(rows), exist_slab)
                per = jnp.sum(
                    jax.lax.population_count(field_block & src[:, None, :]),
                    axis=-1,
                    dtype=jnp.uint32,
                )  # [S, R]
                if per.shape[0] <= MAX_DEVICE_SUM_SHARDS:
                    return jnp.sum(per, axis=0, dtype=jnp.uint32)
                return per

        elif kind == "count_batch":

            @jax.jit
            def fn(blocks, rows, exist_block):
                exist_slab = (
                    exist_block[:, 0, :] if exist_block is not None else None
                )
                slab = _eval_spec(spec, iter(blocks), iter(rows), exist_slab, batched=True)
                per = jnp.sum(
                    jax.lax.population_count(slab), axis=-1, dtype=jnp.uint32
                )  # [S, Q]
                if per.shape[0] <= MAX_DEVICE_SUM_SHARDS:
                    return jnp.sum(per, axis=0, dtype=jnp.uint32)  # [Q]
                return per

        else:  # topn_plain

            @jax.jit
            def fn(field_block):
                per = jnp.sum(
                    jax.lax.population_count(field_block), axis=-1, dtype=jnp.uint32
                )  # [S, R]
                if per.shape[0] <= MAX_DEVICE_SUM_SHARDS:
                    return jnp.sum(per, axis=0, dtype=jnp.uint32)
                return per

        self._fns[key] = fn
        return fn

    # -- backend interface -------------------------------------------------

    def bitmap_call_shard(self, index: str, c: Call, shard: int) -> Row:
        if not self._device_supported(c):
            return self.cpu.bitmap_call_shard(index, c, shard)
        spec = _tree_key(c)
        blocks, rows, exist = self._assemble(index, c, (shard,), spec)
        slab = self._program("vec", spec, exist is not None)(blocks, rows, exist)
        return Row.from_segment(shard, Bitmap(unpack_row(np.asarray(slab[0]))))

    def count_shard(self, index: str, c: Call, shard: int) -> int:
        return self.count_shards(index, c, [shard])

    def count_shards(self, index: str, c: Call, shards: list[int]) -> int:
        """Whole-query count: ONE jitted dispatch over all shards + one
        scalar readback — the reference's scatter-gather mapReduce
        collapsed into device arithmetic (BASELINE.json north star)."""
        if not self._device_supported(c):
            return sum(self.cpu.count_shard(index, c, s) for s in shards)
        spec = _tree_key(c)
        blocks, rows, exist = self._assemble(index, c, tuple(shards), spec)
        partials = self._program("count", spec, exist is not None)(blocks, rows, exist)
        # Host sum in Python ints: exact for any shard count.
        return int(np.asarray(partials, dtype=np.uint64).sum())

    def count_batch(self, index: str, calls: list[Call], shards: list[int]) -> list[int]:
        """Q same-shape count queries in ONE dispatch: row ids become [Q]
        vectors, the fused program computes all counts, and one [Q] vector
        reads back. This is the serving-batch path that makes QPS scale
        past the per-dispatch round-trip floor."""
        if not calls:
            return []
        spec = _tree_key(calls[0])
        assert all(_tree_key(c) == spec for c in calls), "count_batch requires same-shape queries"
        if not self._device_supported(calls[0]):
            return [self.count_shards(index, c, shards) for c in calls]
        shards_t = tuple(shards)
        per_call = [self._assemble(index, c, shards_t, spec) for c in calls]
        blocks = per_call[0][0]
        n_leaves = len(per_call[0][1]) // 2
        rows = []
        for leaf in range(n_leaves):
            rows.append(np.array([pc[1][2 * leaf] for pc in per_call], dtype=np.uint32))
            rows.append(np.array([pc[1][2 * leaf + 1] for pc in per_call], dtype=np.uint32))
        exist = per_call[0][2]
        out = np.asarray(
            self._program("count_batch", spec, exist is not None)(
                blocks, tuple(rows), exist
            ),
            dtype=np.uint64,
        )
        if out.ndim == 2:  # [S, Q] partials past the device-sum bound
            out = out.sum(axis=0)
        return [int(v) for v in out]

    # -- exact TopN (device fast path) -------------------------------------

    def topn_field(
        self,
        index: str,
        field_name: str,
        shards: list[int],
        n: int,
        src_call: Optional[Call] = None,
    ) -> Optional[list[Pair]]:
        """Exact TopN in one dispatch: per-row popcounts of the stacked
        field block (optionally masked by a src tree), reduced over the
        shard axis on device; the counts vector reads back once."""
        if src_call is not None and not self._device_supported(src_call):
            return None
        idx = self.holder.index(index)
        f = idx.field(field_name) if idx else None
        if f is None:
            raise QueryError(f"field not found: {field_name}")
        if f.view(VIEW_STANDARD) is None:
            return []
        shards_t = tuple(shards)
        block, _ = self.blocks.get(index, f, shards_t)

        if src_call is None:
            counts = self._program("topn_plain", ("plain",), False)(block)
        else:
            spec = _tree_key(src_call)
            blocks, rows, exist = self._assemble(index, src_call, shards_t, spec)
            counts = self._program("topn_src", spec, exist is not None)(
                block, blocks, rows, exist
            )
        counts = np.asarray(counts, dtype=np.uint64)
        if counts.ndim == 2:  # [S, R] partials past the device-sum bound
            counts = counts.sum(axis=0)
        order = np.lexsort((np.arange(counts.size), -counts.astype(np.int64)))
        pairs = [Pair(id=int(r), count=int(counts[r])) for r in order if counts[r] > 0]
        return pairs[:n] if n else pairs
