"""Unified shard-leg batching plane: cross-request device-launch coalescing.

BENCH_r04 showed the served path is dispatch-bound, not compute-bound:
`single_query_p50_ms` ≈ 131 ms against a device sweep of ~2.7 ms, with a
~112 ms relay round-trip floor paid PER LAUNCH. The fix is the standard
TPU-serving answer to many small heterogeneous requests (the
fixed-shape-slot / ragged-occupancy trick of "Ragged Paged Attention",
PAPERS.md): concurrent queries' device dispatches — Count, bitmap
Row/Intersect/Union resolves, BSI Sum/Min/Max, TopN per-shard counts —
are enqueued as typed LEG descriptors, and a drain groups compatible legs
by (kind, index, shard set) so ONE device launch (exec/tpu.py batched
programs: fixed-shape slot arrays, padded to a slot-count bucket, inactive
lanes masked in-kernel, a per-slot query-id vector scattering results
back) answers the whole group.

Scheduling is the proven leader/follower backpressure loop (VERDICT r2
#2, ADVICE r3): the first submitter becomes leader and dispatches its
batch IMMEDIATELY (no coalescing sleep — an uncontended single leg pays
zero added latency); legs arriving while the leader's dispatch is in
flight queue behind the leadership flag and drain as the NEXT batch (by a
detached helper thread, so the leader's own HTTP response returns as soon
as its leg resolves). Batching therefore emerges from backpressure: the
busier the device round trip, the larger the coalesced batches, with no
idle window on a quiet server. `window > 0` restores a fixed coalescing
sleep for tests that need deterministic batch composition.

Coalescing strategy per kind:
- count: every group's calls concatenate into one backend
  count_batch_async (pair-stats fast path or slot-bucketed fused scans).
- row: calls share one slot-bucketed scanned launch per (spec, blocks)
  group via row_batch_async; identical specs dedupe to one slot.
- bsi_sum/bsi_min/bsi_max and topn: identical legs (same field + filter
  tree) dedupe to ONE backend call — the concurrent-hot-query case that
  dominates serving traffic — and the backend's epoch caches make the
  deduped call itself usually a host hit.

Telemetry: each dispatched group observes its occupancy — legs per
coalesced launch GROUP — into the `batch_occupancy{kind=…}` histogram
and counts `batch_legs_total{kind=…}` / `batch_coalesced_total{kind=…}`;
the backend counts every real program execution as
`device_launches_total{kind=…}` at the compiled-program chokepoint.
A group usually maps to one launch, but heterogeneous specs or a
byte-capped row group can fan one group into several, so compare
batch_legs_total against device_launches_total for the exact
coalescing ratio; occupancy is the per-drain grouping view. Followers
attribute their whole cost to the `batch_wait` profile phase; the
leader's dispatch work self-attributes (`device_dispatch` et al.) inside
the backend calls it makes on behalf of the batch.

Mesh composition (ISSUE r13): when the backend carries a ShardMesh,
every launch this plane coalesces — count_batch/vec_batch scans, the
pair-stats sweep, BSI aggregates, TopN popcounts — runs under
shard_map on the sharded stacks with psum/all_gather merges over ICI;
the leg descriptors, group keys, and power-of-two slot buckets are
identical in both regimes (slot padding is a query-axis concern,
orthogonal to the shard axis the mesh splits), so nothing here
branches on topology. Coalescing matters MORE under a mesh: each
launch is a collective across every chip, so the per-launch overhead
the leader/follower drain amortizes is multiplied by the device
count. The backend's [Q, S, W] row-batch byte cap is per-device there
(exec/tpu.py row_batch_async), so mesh row groups chunk n-fold less.

Error isolation: a failed group dispatch retries each member leg
individually so one client's bad query (unknown field, unsupported
shape) errors only that client, never the whole window. Only Exception
is absorbed into the retry path; KeyboardInterrupt/SystemExit in the
drain thread propagates after waiters are released (ADVICE r3).

The reference has no analog: the Go engine executes each request's calls
serially per connection (executor.go:231) because its per-shard loop is
already CPU-parallel. On a TPU the economics invert — dispatches are
expensive, device sweeps are cheap — so coalescing across requests is
what makes the serving path reach the batched-kernel throughput.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Optional

from pilosa_tpu.utils.locks import InstrumentedLock
from pilosa_tpu.utils.qprofile import current_profile
from pilosa_tpu.utils.stats import global_stats
from pilosa_tpu.utils.threads import spawn

#: Leg kinds the plane coalesces. count/row/topn legs are built only by
#: their own submit methods; bsi() takes the kind as an argument and
#: validates it against the bsi_ subset below.
LEG_KINDS = ("count", "row", "bsi_sum", "bsi_min", "bsi_max", "topn")


class _Leg:
    """One enqueued shard-leg: a typed descriptor plus its rendezvous."""

    __slots__ = ("kind", "index", "shards", "payload", "event", "result",
                 "error", "explain", "explain_rec")

    def __init__(self, kind: str, index: str, shards, payload):
        self.kind = kind
        self.index = index
        self.shards = shards  # tuple — part of the group key
        self.payload = payload
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        # EXPLAIN (ISSUE 16): the submitter's plan leg-sink, captured at
        # construction ON THE SUBMITTING THREAD so the leader can
        # attribute this leg's group record into the right plan. None
        # when the submitter carries no plan (the common case) — the
        # batching plane then allocates nothing.
        ex = getattr(current_profile(), "explain", None)
        self.explain = ex.leg_sink() if ex is not None else None
        self.explain_rec: Optional[dict] = None


class ShardLegBatcher:
    """Leader/follower backpressure batcher over the device backend's
    batched entry points (count_batch_async / row_batch_async /
    bsi_* / topn_field).

    window > 0 restores the fixed coalescing sleep before each drain
    (useful for tests that need deterministic batch composition); the
    production default is 0 — see module docstring.
    """

    def __init__(self, backend, window: float = 0.0):
        self.backend = backend
        self.window = window
        self._lock = InstrumentedLock("batcher_drain")
        self._pending: list[_Leg] = []
        self._leader_active = False
        self.stats = global_stats
        # EXPLAIN group ids: process-unique per batcher, so two legs of
        # one query showing the same id PROVES they shared a drain
        # group (itertools.count: GIL-atomic, no lock).
        self._group_ids = itertools.count(1)

    # -- public submit API (one method per leg kind) -----------------------

    def count(self, index: str, calls: list, shards: list[int]) -> list[int]:
        """Block until the batch containing these Count calls resolves;
        returns one count per call. Thread-safe; any thread may become
        leader."""
        return self._submit(_Leg("count", index, tuple(shards), list(calls)))

    def row(self, index: str, call, shards: list[int]):
        """Bitmap materialization (Row/Intersect/Union/... resolve):
        returns the merged Row for the shard set."""
        return self._submit(_Leg("row", index, tuple(shards), call))

    def bsi(self, kind: str, index: str, field_name: str, shards: list[int],
            filter_call=None):
        """BSI aggregate (kind: bsi_sum | bsi_min | bsi_max). Returns the
        backend's (value, count) tuple, or None when not lowerable (the
        executor then runs its map-reduce path)."""
        if kind not in LEG_KINDS or not kind.startswith("bsi_"):
            raise ValueError(f"unknown bsi leg kind: {kind!r}")
        return self._submit(
            _Leg(kind, index, tuple(shards), (field_name, filter_call))
        )

    def topn(self, index: str, field_name: str, shards: list[int], n: int,
             src_call=None):
        """Exact TopN pairs (or None when not device-servable). The
        backend computes the FULL ranked vector once per unique
        (field, src) leg; n trims per submitter at scatter time, so
        TopN(n=5) and TopN(n=50) on the same field share one launch."""
        pairs = self._submit(
            _Leg("topn", index, tuple(shards), (field_name, src_call))
        )
        if pairs is None:
            return None
        return pairs[:n] if n else list(pairs)

    # -- leader/follower drain ---------------------------------------------

    def _submit(self, leg: _Leg):
        with self._lock:
            self._pending.append(leg)
            am_leader = not self._leader_active
            if am_leader:
                self._leader_active = True
        if am_leader:
            self._drain(leader_call=True)
        # Telemetry: a follower's whole cost is this wait (the leader's
        # dispatch work self-attributes inside the backend calls); for
        # the leader the event is already set and the phase is ~0.
        with current_profile().phase("batch_wait"):
            leg.event.wait()
        if leg.error is not None:
            raise leg.error
        return leg.result

    def _drain(self, leader_call: bool) -> None:
        """Serve queued batches. A leader (client thread) serves exactly
        ONE batch — its own leg resolves in it — then hands any queue
        that built up during the round trip to a detached helper thread,
        so under sustained load the first client's HTTP response is not
        held open serving everyone else's batches (code review r4). The
        helper loops until the queue is empty; leadership is released
        under the lock, so a concurrent submitter either sees pending
        work claimed or becomes the next leader itself — never neither."""
        if leader_call and self.window > 0:
            # Optional fixed coalescing window before the leader's first
            # (only) drain; helper threads never sleep — the device round
            # trip itself is their window.
            time.sleep(self.window)
        while True:
            with self._lock:
                batch = self._pending
                self._pending = []
                if not batch:
                    self._leader_active = False
                    return
            try:
                self._serve(batch)
            except BaseException:
                # KeyboardInterrupt/SystemExit (or a bug in _serve): free
                # the waiters — INCLUDING followers already queued behind
                # this leadership, who would otherwise wait forever with
                # no leader — and release leadership before propagating.
                err = RuntimeError("shard-leg batch leader interrupted")
                with self._lock:
                    stranded = self._pending
                    self._pending = []
                    self._leader_active = False
                for leg in batch + stranded:
                    if not leg.event.is_set():
                        leg.error = err
                        leg.event.set()
                raise
            if leader_call:
                with self._lock:
                    if not self._pending:
                        self._leader_active = False
                        return
                spawn("batcher-leader", self._drain, args=(False,))
                return

    # -- batch service ------------------------------------------------------

    def _serve(self, batch: list[_Leg]) -> None:
        """Group the drained window by (kind, index, shard set), dispatch
        every async-capable group BEFORE resolving any (XLA pipelines the
        device work past the readback round trips), then run the
        synchronous groups and scatter results back by leg."""
        groups: dict[tuple, list[_Leg]] = {}
        for leg in batch:
            groups.setdefault((leg.kind, leg.index, leg.shards), []).append(leg)
        pending = []  # (legs, resolver) for async kinds
        sync_groups = []
        for (kind, index, shards), legs in groups.items():
            self._observe_group(kind, legs)
            if kind == "count":
                pending.append((legs, self._dispatch_count(index, shards, legs)))
            elif kind == "row":
                pending.append((legs, self._dispatch_row(index, shards, legs)))
            else:
                sync_groups.append((kind, index, shards, legs))
        # Synchronous kinds (bsi_*/topn) run AFTER every async dispatch is
        # in flight, so their host/cache work overlaps the device round
        # trips instead of serializing ahead of them.
        for kind, index, shards, legs in sync_groups:
            self._serve_sync(kind, index, shards, legs)
        for legs, resolver in pending:
            if resolver is None:
                continue  # already resolved individually by the dispatcher
            try:
                resolver()
            except Exception:
                # Shared-launch resolution failed: visible on /metrics,
                # then isolate so one bad query can't fail the window.
                self.stats.with_tags(f"kind:{legs[0].kind}").count(
                    "batch_dispatch_errors_total"
                )
                self._resolve_individually(legs)

    def _observe_group(self, kind: str, legs: list[_Leg]) -> None:
        st = self.stats.with_tags(f"kind:{kind}")
        st.count("batch_legs_total", len(legs))
        if len(legs) > 1:
            st.count("batch_coalesced_total", len(legs) - 1)
        # Occupancy histogram: legs per coalesced launch group (unit:
        # legs, not seconds — the shared bucket set covers 1..100 with
        # 5 buckets/decade; the mean from _sum/_count is exact).
        st.timing("batch_occupancy", float(len(legs)))
        if any(leg.explain is not None for leg in legs):
            occ = len(legs)
            gid = next(self._group_ids)
            bucket = 1 if occ <= 1 else 1 << (occ - 1).bit_length()
            for leg in legs:
                if leg.explain is None:
                    continue
                rec = {
                    "group": gid,
                    "kind": kind,
                    "occupancy": occ,
                    "occupancyBucket": bucket,
                    "shards": len(leg.shards),
                }
                leg.explain.append(rec)
                leg.explain_rec = rec

    # -- count legs ---------------------------------------------------------

    def _dispatch_count(self, index, shards, legs):
        all_calls = [c for leg in legs for c in leg.payload]
        try:
            resolver = self.backend.count_batch_async(
                index, all_calls, list(shards)
            )
        except Exception:
            self.stats.with_tags("kind:count").count(
                "batch_dispatch_errors_total"
            )
            self._resolve_individually(legs)
            return None

        def resolve():
            values = resolver()
            off = 0
            for leg in legs:
                n = len(leg.payload)
                leg.result = [int(v) for v in values[off : off + n]]
                off += n
                leg.event.set()

        return resolve

    # -- row legs -----------------------------------------------------------

    def _dispatch_row(self, index, shards, legs):
        try:
            resolver = self.backend.row_batch_async(
                index, [leg.payload for leg in legs], list(shards)
            )
        except Exception:
            self.stats.with_tags("kind:row").count(
                "batch_dispatch_errors_total"
            )
            self._resolve_individually(legs)
            return None

        def resolve():
            rows = resolver()
            for leg, row in zip(legs, rows):
                leg.result = row
                leg.event.set()

        return resolve

    # -- synchronous kinds (bsi aggregates, topn) ---------------------------

    def _serve_sync(self, kind, index, shards, legs) -> None:
        """Dedupe identical legs (same field + same filter tree object —
        parse-cached trees make repeated hot queries literally identical)
        to one backend call each; every member leg of a dedupe set gets
        the shared immutable result."""
        by_payload: dict[tuple, list[_Leg]] = {}
        for leg in legs:
            field_name, filt = leg.payload
            by_payload.setdefault((field_name, id(filt) if filt is not None else None), []).append(leg)
        for (field_name, _fid), members in by_payload.items():
            filt = members[0].payload[1]
            for leg in members:
                if leg.explain_rec is not None:
                    # Slot-dedupe outcome: `shared` means this leg rode
                    # another identical leg's backend call.
                    leg.explain_rec["dedupe"] = (
                        "shared" if len(members) > 1 else "unique"
                    )
            try:
                if kind == "topn":
                    # n=0: the full ranked vector — submitters trim in
                    # topn() so different n's share the launch.
                    result = self.backend.topn_field(
                        index, field_name, list(shards), 0, filt
                    )
                else:
                    result = getattr(self.backend, kind)(
                        index, field_name, list(shards), filt
                    )
            except Exception as e:  # noqa: BLE001 — delivered to waiters
                for leg in members:
                    leg.error = e
                    leg.event.set()
                continue
            for leg in members:
                leg.result = result
                leg.event.set()

    # -- error isolation ----------------------------------------------------

    def _resolve_individually(self, legs: list[_Leg]) -> None:
        """Group dispatch failed — isolate: one dispatch per leg so only
        the offending client sees the error."""
        for leg in legs:
            try:
                if leg.kind == "count":
                    resolver = self.backend.count_batch_async(
                        leg.index, leg.payload, list(leg.shards)
                    )
                    leg.result = [int(v) for v in resolver()]
                elif leg.kind == "row":
                    leg.result = self.backend.bitmap_call(
                        leg.index, leg.payload, list(leg.shards)
                    )
                else:  # bsi_*/topn legs retry through _serve_sync directly
                    self._serve_sync(
                        leg.kind, leg.index, leg.shards, [leg]
                    )
                    continue
            except Exception as e:  # noqa: BLE001 — delivered to waiter
                leg.error = e
            leg.event.set()


#: Backward-compatible name: the plane grew out of the Count-only
#: coalescer and every wiring site (cli, bench, tests) used this name.
CountBatcher = ShardLegBatcher
