"""Cross-request micro-batching for Count queries (VERDICT r2 #2).

Concurrent HTTP clients each issue small Count requests; one device
dispatch can serve hundreds of them (the pair-stats kernel touches each
HBM byte once per sweep regardless of how many queries it answers). The
batcher coalesces concurrent submissions with a leader/follower window:
the first submitter becomes leader, sleeps `window` seconds — small
against the ~78 ms relay dispatch round trip — then drains the queue,
groups items by (index, shards), and issues ONE count_batch_async per
group, distributing results back to the waiting threads.

The reference has no analog: the Go engine executes each request's calls
serially per connection (executor.go:231) because its per-shard loop is
already CPU-parallel. On a TPU the economics invert — dispatches are
expensive, device sweeps are cheap — so coalescing across requests is
what makes the serving path reach the batched-kernel throughput.

Error isolation: a failed group dispatch retries each member item
individually so one client's bad query (unknown field, unsupported
shape) errors only that client, never the whole window.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from pilosa_tpu.utils.stats import global_stats


class _Item:
    __slots__ = ("index", "shards", "calls", "event", "result", "error")

    def __init__(self, index, shards, calls):
        self.index = index
        self.shards = shards
        self.calls = calls
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class CountBatcher:
    """Leader/follower window batcher over TPUBackend.count_batch_async."""

    def __init__(self, backend, window: float = 0.004):
        self.backend = backend
        self.window = window
        self._lock = threading.Lock()
        self._pending: list[_Item] = []
        self._leader_active = False
        self.stats = global_stats

    def count(self, index: str, calls: list, shards: list[int]) -> list[int]:
        """Block until the batch containing these calls resolves; returns
        one count per call. Thread-safe; any thread may become leader."""
        item = _Item(index, tuple(shards), list(calls))
        with self._lock:
            self._pending.append(item)
            am_leader = not self._leader_active
            if am_leader:
                self._leader_active = True
        if am_leader:
            self._lead()
        item.event.wait()
        if item.error is not None:
            raise item.error
        return item.result  # type: ignore[return-value]

    # ------------------------------------------------------------------

    def _lead(self) -> None:
        # Sleep the coalescing window so concurrent submitters can pile
        # on, then drain. New arrivals after the drain elect a new leader.
        if self.window > 0:
            time.sleep(self.window)
        with self._lock:
            batch = self._pending
            self._pending = []
            self._leader_active = False
        if not batch:
            return
        n_queries = sum(len(it.calls) for it in batch)
        self.stats.count("count_batcher_batches_total")
        self.stats.count("count_batcher_queries_total", n_queries)
        if len(batch) > 1:
            self.stats.count("count_batcher_coalesced_total", len(batch) - 1)
        groups: dict[tuple, list[_Item]] = {}
        for it in batch:
            groups.setdefault((it.index, it.shards), []).append(it)
        # Dispatch every group before resolving any: the async resolvers
        # let XLA pipeline the device work past the readback round trips.
        dispatched = []
        for (index, shards), items in groups.items():
            all_calls = [c for it in items for c in it.calls]
            try:
                resolver = self.backend.count_batch_async(
                    index, all_calls, list(shards)
                )
            except BaseException:
                dispatched.append((items, None))
                continue
            dispatched.append((items, resolver))
        for items, resolver in dispatched:
            if resolver is None:
                self._resolve_individually(items)
                continue
            try:
                values = resolver()
            except BaseException:
                self._resolve_individually(items)
                continue
            off = 0
            for it in items:
                it.result = [int(v) for v in values[off : off + len(it.calls)]]
                off += len(it.calls)
                it.event.set()

    def _resolve_individually(self, items: list[_Item]) -> None:
        """Group dispatch failed — isolate: one dispatch per item so only
        the offending client sees the error."""
        for it in items:
            try:
                resolver = self.backend.count_batch_async(
                    it.index, it.calls, list(it.shards)
                )
                it.result = [int(v) for v in resolver()]
            except BaseException as e:  # noqa: BLE001 — delivered to waiter
                it.error = e
            it.event.set()
