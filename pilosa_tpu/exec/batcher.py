"""Cross-request micro-batching for Count queries (VERDICT r2 #2).

Concurrent HTTP clients each issue small Count requests; one device
dispatch can serve hundreds of them (the pair-stats kernel touches each
HBM byte once per sweep regardless of how many queries it answers). The
batcher coalesces concurrent submissions with a leader/follower loop:
the first submitter becomes leader and dispatches its batch IMMEDIATELY
(no coalescing sleep — an uncontended single Count pays zero added
latency, ADVICE r3); requests arriving while the leader's dispatch is in
flight queue up behind the leadership flag and are drained as the NEXT
batch (by a detached helper thread, so the leader's own HTTP response
returns as soon as its item resolves). Batching therefore emerges
from backpressure: the busier the device round trip (~78 ms on a relay-
attached chip), the larger the coalesced batches, with no idle window on
a quiet server.

The reference has no analog: the Go engine executes each request's calls
serially per connection (executor.go:231) because its per-shard loop is
already CPU-parallel. On a TPU the economics invert — dispatches are
expensive, device sweeps are cheap — so coalescing across requests is
what makes the serving path reach the batched-kernel throughput.

Error isolation: a failed group dispatch retries each member item
individually so one client's bad query (unknown field, unsupported
shape) errors only that client, never the whole window. Only Exception
is absorbed into the retry path; KeyboardInterrupt/SystemExit in the
leader thread propagates after waiters are released (ADVICE r3).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from pilosa_tpu.utils.qprofile import current_profile
from pilosa_tpu.utils.stats import global_stats


class _Item:
    __slots__ = ("index", "shards", "calls", "event", "result", "error")

    def __init__(self, index, shards, calls):
        self.index = index
        self.shards = shards
        self.calls = calls
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class CountBatcher:
    """Leader/follower backpressure batcher over count_batch_async.

    window > 0 restores the fixed coalescing sleep before each drain
    (useful for tests that need deterministic batch composition); the
    production default is 0 — see module docstring.
    """

    def __init__(self, backend, window: float = 0.0):
        self.backend = backend
        self.window = window
        self._lock = threading.Lock()
        self._pending: list[_Item] = []
        self._leader_active = False
        self.stats = global_stats

    def count(self, index: str, calls: list, shards: list[int]) -> list[int]:
        """Block until the batch containing these calls resolves; returns
        one count per call. Thread-safe; any thread may become leader."""
        item = _Item(index, tuple(shards), list(calls))
        with self._lock:
            self._pending.append(item)
            am_leader = not self._leader_active
            if am_leader:
                self._leader_active = True
        if am_leader:
            self._drain(leader_call=True)
        # Telemetry: a follower's whole cost is this wait (the leader's
        # dispatch work self-attributes inside count_batch_async); for
        # the leader the event is already set and the phase is ~0.
        with current_profile().phase("batch_wait"):
            item.event.wait()
        if item.error is not None:
            raise item.error
        return item.result  # type: ignore[return-value]

    # ------------------------------------------------------------------

    def _drain(self, leader_call: bool) -> None:
        """Serve queued batches. A leader (client thread) serves exactly
        ONE batch — its own item resolves in it — then hands any queue
        that built up during the round trip to a detached helper thread,
        so under sustained load the first client's HTTP response is not
        held open serving everyone else's batches (code review r4). The
        helper loops until the queue is empty; leadership is released
        under the lock, so a concurrent submitter either sees pending
        work claimed or becomes the next leader itself — never neither."""
        if leader_call and self.window > 0:
            # Optional fixed coalescing window before the leader's first
            # (only) drain; helper threads never sleep — the device round
            # trip itself is their window.
            time.sleep(self.window)
        while True:
            with self._lock:
                batch = self._pending
                self._pending = []
                if not batch:
                    self._leader_active = False
                    return
            try:
                self._serve(batch)
            except BaseException:
                # KeyboardInterrupt/SystemExit (or a bug in _serve): free
                # the waiters — INCLUDING followers already queued behind
                # this leadership, who would otherwise wait forever with
                # no leader — and release leadership before propagating.
                err = RuntimeError("count batch leader interrupted")
                with self._lock:
                    stranded = self._pending
                    self._pending = []
                    self._leader_active = False
                for it in batch + stranded:
                    if not it.event.is_set():
                        it.error = err
                        it.event.set()
                raise
            if leader_call:
                with self._lock:
                    if not self._pending:
                        self._leader_active = False
                        return
                threading.Thread(
                    target=self._drain, args=(False,), daemon=True
                ).start()
                return

    def _serve(self, batch: list[_Item]) -> None:
        n_queries = sum(len(it.calls) for it in batch)
        self.stats.count("count_batcher_batches_total")
        self.stats.count("count_batcher_queries_total", n_queries)
        if len(batch) > 1:
            self.stats.count("count_batcher_coalesced_total", len(batch) - 1)
        groups: dict[tuple, list[_Item]] = {}
        for it in batch:
            groups.setdefault((it.index, it.shards), []).append(it)
        # Dispatch every group before resolving any: the async resolvers
        # let XLA pipeline the device work past the readback round trips.
        dispatched = []
        for (index, shards), items in groups.items():
            all_calls = [c for it in items for c in it.calls]
            try:
                resolver = self.backend.count_batch_async(
                    index, all_calls, list(shards)
                )
            except Exception:
                dispatched.append((items, None))
                continue
            dispatched.append((items, resolver))
        for items, resolver in dispatched:
            if resolver is None:
                self._resolve_individually(items)
                continue
            try:
                values = resolver()
            except Exception:
                self._resolve_individually(items)
                continue
            off = 0
            for it in items:
                it.result = [int(v) for v in values[off : off + len(it.calls)]]
                off += len(it.calls)
                it.event.set()

    def _resolve_individually(self, items: list[_Item]) -> None:
        """Group dispatch failed — isolate: one dispatch per item so only
        the offending client sees the error."""
        for it in items:
            try:
                resolver = self.backend.count_batch_async(
                    it.index, it.calls, list(it.shards)
                )
                it.result = [int(v) for v in resolver()]
            except Exception as e:  # noqa: BLE001 — delivered to waiter
                it.error = e
            it.event.set()
