"""pilosa_tpu — a TPU-native distributed bitmap index.

A ground-up re-design of Pilosa (reference: /root/reference, Go) for TPU:
the storage hierarchy (holder -> index -> field -> view -> fragment), the PQL
query language and the HTTP API are kept compatible, but query execution lowers
to XLA/Pallas bitwise + popcount kernels over dense HBM-resident bitmap blocks,
with shard fan-out via jax shard_map over a device mesh and reductions riding
ICI collectives (lax.psum / top_k merges) instead of HTTP map-reduce.
"""

__version__ = "0.1.0"

from pilosa_tpu.shardwidth import SHARD_WIDTH, SHARD_WIDTH_EXP
