"""Multi-chip parallelism: shard placement over a jax device mesh.

The reference scatters shards to cluster nodes over HTTP and reduces
streaming responses (reference executor.go mapReduce :2460, cluster.go
jump-hash placement :871). Intra-host/pod, this layer replaces that wire
protocol with a jax.sharding.Mesh over a 'shards' axis: stacked fragment
blocks live sharded across devices, per-device partial results are
computed by shard_map-ed kernels, and reductions ride ICI collectives
(lax.psum for Count/Sum, gathered top_k for TopN). Cross-host (DCN)
traffic remains RPC at the cluster layer (pilosa_tpu/cluster).
"""

from pilosa_tpu.parallel.mesh import MeshConfigError, ShardMesh, pad_to_multiple
