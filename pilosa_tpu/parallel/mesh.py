"""Device-mesh execution of shard-parallel queries.

A ShardMesh owns a 1-D jax Mesh over the 'shards' axis. Query-side arrays
are stacked [n_shards, ...] and placed with NamedSharding(P('shards')),
so each device holds its shards' blocks in local HBM; shard_map-ed
kernels compute per-device partials and psum/all_gather them over ICI —
the XLA-collective replacement for the reference's HTTP scatter-gather
(SURVEY.md §2.2).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ShardMesh:
    def __init__(self, devices: Optional[Sequence] = None, axis: str = "shards"):
        if devices is None:
            devices = jax.devices()
        self.axis = axis
        self.devices = list(devices)
        self.mesh = Mesh(np.array(self.devices), (axis,))
        self.n = len(self.devices)
        self._sharding = NamedSharding(self.mesh, P(axis))

        axis_name = axis

        @jax.jit
        def _count_and(a, b):
            # a, b: uint32[S, W] sharded over 'shards'. AND+popcount locally,
            # psum partials over ICI -> replicated scalar.
            def kernel(a_blk, b_blk):
                part = jnp.sum(
                    jax.lax.population_count(a_blk & b_blk), dtype=jnp.uint32
                )
                return jax.lax.psum(part, axis_name)

            return shard_map(
                kernel,
                mesh=self.mesh,
                in_specs=(P(axis_name, None), P(axis_name, None)),
                out_specs=P(),
            )(a, b)

        self._count_and = _count_and

        @jax.jit
        def _topn_counts(blocks):
            # blocks: uint32[S, R, W] sharded over 'shards'. Per-row
            # popcount locally, psum row-count vectors over ICI.
            def kernel(blk):
                per_row = jnp.sum(
                    jax.lax.population_count(blk), axis=(0, 2), dtype=jnp.uint32
                )
                return jax.lax.psum(per_row, axis_name)

            return shard_map(
                kernel,
                mesh=self.mesh,
                in_specs=(P(axis_name, None, None),),
                out_specs=P(),
            )(blocks)

        self._topn_counts = _topn_counts

        @jax.jit
        def _bsi_sum(planes, exists, sign):
            # planes: uint32[S, D, W]; exists/sign: uint32[S, W], all
            # sharded over 'shards'. Per-plane popcounts psum'd over ICI;
            # final weighting on host in exact ints.
            def kernel(planes_blk, exists_blk, sign_blk):
                consider = exists_blk
                neg = sign_blk & consider
                pos = consider & ~neg
                pos_c = jnp.sum(
                    jax.lax.population_count(planes_blk & pos[:, None, :]),
                    axis=(0, 2),
                    dtype=jnp.uint32,
                )
                neg_c = jnp.sum(
                    jax.lax.population_count(planes_blk & neg[:, None, :]),
                    axis=(0, 2),
                    dtype=jnp.uint32,
                )
                cnt = jnp.sum(jax.lax.population_count(consider), dtype=jnp.uint32)
                return (
                    jax.lax.psum(pos_c, axis_name),
                    jax.lax.psum(neg_c, axis_name),
                    jax.lax.psum(cnt, axis_name),
                )

            return shard_map(
                kernel,
                mesh=self.mesh,
                in_specs=(P(axis_name, None, None), P(axis_name, None), P(axis_name, None)),
                out_specs=(P(), P(), P()),
            )(planes, exists, sign)

        self._bsi_sum = _bsi_sum

    # -- public API -------------------------------------------------------

    def put(self, host_array: np.ndarray):
        """Place a [n_shards, ...] stacked array sharded over the mesh."""
        assert host_array.shape[0] % self.n == 0, (
            f"leading dim {host_array.shape[0]} not divisible by {self.n} devices"
        )
        return jax.device_put(host_array, self._sharding)

    def count_intersect(self, a, b) -> int:
        """Count(Intersect(a, b)) across the mesh: AND+popcount per device,
        psum over ICI."""
        return int(self._count_and(a, b))

    def topn_counts(self, blocks) -> np.ndarray:
        """Exact per-row counts across all shards: [S, R, W] -> [R]."""
        return np.asarray(self._topn_counts(blocks))

    def bsi_sum(self, planes, exists, sign) -> tuple[int, int]:
        """Distributed BSI sum -> (sum, count), weighting on host."""
        pos_c, neg_c, cnt = self._bsi_sum(planes, exists, sign)
        pos_c, neg_c = np.asarray(pos_c), np.asarray(neg_c)
        total = sum((int(pos_c[i]) - int(neg_c[i])) << i for i in range(pos_c.size))
        # note: pos-neg per plane then weight — matches reference
        # fragment.sum's psum-nsum squashing (fragment.go:1131-1139).
        return total, int(cnt)
