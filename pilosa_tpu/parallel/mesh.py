"""Device-mesh execution of shard-parallel queries.

A ShardMesh owns a 1-D jax Mesh over the 'shards' axis. The TPU backend
stacks query-side arrays [n_shards, ...] and places them with
NamedSharding(P('shards')), so each device holds its shards' blocks in
local HBM; shard_map-ed programs compute per-device partials and
psum/all_gather them over ICI — the XLA-collective replacement for the
reference's HTTP scatter-gather (SURVEY.md §2.2). The programs
themselves live in exec/tpu.py (TPUBackend._program/_pair_program);
this class is the topology object they build against.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ShardMesh:
    def __init__(self, devices: Optional[Sequence] = None, axis: str = "shards"):
        if devices is None:
            devices = jax.devices()
        self.axis = axis
        self.devices = list(devices)
        self.mesh = Mesh(np.array(self.devices), (axis,))
        self.n = len(self.devices)
        self._sharding = NamedSharding(self.mesh, P(axis))

    def put(self, host_array: np.ndarray):
        """Place a [n_shards, ...] stacked array sharded over the mesh."""
        assert host_array.shape[0] % self.n == 0, (
            f"leading dim {host_array.shape[0]} not divisible by {self.n} devices"
        )
        return jax.device_put(host_array, self._sharding)
