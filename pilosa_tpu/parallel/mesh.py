"""Device-mesh execution of shard-parallel queries.

A ShardMesh owns a 1-D jax Mesh over the 'shards' axis. The TPU backend
stacks query-side arrays [n_shards, ...] and places them with
NamedSharding(P('shards')), so each device holds its shards' blocks in
local HBM; shard_map-ed programs compute per-device partials and
psum/all_gather them over ICI — the XLA-collective replacement for the
reference's HTTP scatter-gather (SURVEY.md §2.2). The programs
themselves live in exec/tpu.py (TPUBackend._program/_pair_program);
this class is the topology object they build against.

Padding contract: shard_map needs the leading (shard) axis divisible by
the device count, so placements pad it up to the next multiple with
ALL-ZERO slabs. Zero slabs are semantically inert everywhere the
backend reduces — a zero bitmap word contributes nothing to any
popcount, bitwise verb, BSI plane scan, or pair/group matrix cell — so
padded positions never change an answer; hosts that slice results
per-shard simply stop at the real shard count.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class MeshConfigError(ValueError):
    """A ShardMesh cannot be built from the given device set (empty
    device list — e.g. a mesh-devices count larger than the platform
    offers after slicing). Structured so callers can distinguish a
    topology misconfiguration from a generic placement failure."""


def pad_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of m that is >= n — the shared shard-axis
    padding rule (ShardMesh.put and exec/tpu._StackedBlocks._pad_shards
    must agree, or a stack placed by one would mis-shape for the
    other's programs)."""
    if m <= 1:
        return n
    return ((n + m - 1) // m) * m


class ShardMesh:
    def __init__(self, devices: Optional[Sequence] = None, axis: str = "shards"):
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        if not devices:
            raise MeshConfigError(
                "ShardMesh needs at least one device (got an empty device "
                "list; check mesh-devices against the platform inventory)"
            )
        self.axis = axis
        self.devices = devices
        self.mesh = Mesh(np.array(self.devices), (axis,))
        self.n = len(self.devices)
        self._sharding = NamedSharding(self.mesh, P(axis))

    def put(self, host_array: np.ndarray):
        """Place a [n_shards, ...] stacked array sharded over the mesh.
        A leading dim that isn't a multiple of the device count pads up
        with zero slabs (see the module docstring's padding contract) —
        callers keep indexing by their real shard positions and ignore
        the tail."""
        s = host_array.shape[0]
        s_pad = pad_to_multiple(s, self.n)
        if s_pad != s:
            padded = np.zeros((s_pad,) + host_array.shape[1:],
                              dtype=host_array.dtype)
            padded[:s] = host_array
            host_array = padded
        return jax.device_put(host_array, self._sharding)
