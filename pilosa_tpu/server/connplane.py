"""Connection-plane observability: the front-door lifecycle ledger
(ISSUE 20 tentpole 1+2).

BENCH_r13/r14 showed served qps plateauing at ~28k between 16 and 64
clients with `resp_write` growing 0.16 → 9.75 ms, and nothing observed
the front door itself: no accept-to-handler queue-wait number, no
per-connection accounting, no kernel listen-backlog truth. This module
is that accounting plane. It instruments connection lifecycle EVENTS —
accept, dispatch, read, parse, execute, write, idle, close — not the
threading implementation, so the plane survives the ROADMAP item 1
C10k front-door rewrite unchanged.

State machine (per connection)::

    accepted -> queued -> reading -> parsing -> executing -> writing
                   ^                                |           |
                   |        (keep-alive)            v           v
                 closed <------------------------ idle <---- executing

- ``accepted``: the instant between kernel accept and ledger
  registration (~0 by construction).
- ``queued``: waiting for a worker to pick the socket up AND for the
  first request bytes to arrive. The accept-to-handler slice of it is
  ALSO observed into the ``http_queue_wait_seconds`` histogram — the
  thread-dispatch delay the C10k rewrite must collapse.
- ``reading``/``parsing``: request head arrival vs header read +
  validation + eager chunked-body decode.
- ``executing``: route dispatch through handler return (body reads
  included); ``writing`` brackets exactly the response write.
- ``idle``: a keep-alive connection waiting for its next request.

Timing contract: the clock is read ONLY at state transitions — never
per byte — and per-state seconds accumulate on the entry itself
(owner-thread plain-float math, no locks). Aggregate counters
(``http_connection_state_seconds{state}``,
``http_keepalive_reuse_total``) are flushed once per request cycle (at
the transition to ``idle``) and at close, so the serving path pays a
handful of clock reads and one batched stats pass per request.

Kernel-side truth (monitor-poll cadence + /debug/connections scrape):
the listen socket's accept-queue depth from ``/proc/net/tcp{,6}`` and
``ListenOverflows`` / ``ListenDrops`` deltas from
``/proc/net/netstat`` — a full 128-deep ``request_queue_size`` backlog
becomes visible instead of silently RSTing SYNs. Off Linux every probe
is a graceful no-op. Note the TcpExt counters are HOST-wide (the
kernel does not split them per listener); deltas still move exactly
when this process's backlog overflows under bench load.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Optional

from pilosa_tpu.utils.stats import global_stats

#: The full state vocabulary (the `state` metric tag's bounded
#: enumeration for connection series; tools/lint/checkers/metrics.py).
STATES = (
    "accepted", "queued", "reading", "parsing",
    "executing", "writing", "idle", "closed",
)

#: Pre-tagged stats clients, one per state: a transition flush must not
#: allocate a tagged client per request.
_STATE_STATS = {s: global_stats.with_tags(f"state:{s}") for s in STATES}


class _NopEntry:
    """Zero-cost sink for handlers running without a connection plane
    (direct _Handler construction in tests, exotic embeddings): every
    hook is a pass, so the handler code never branches."""

    __slots__ = ()

    def transition(self, state: str) -> None:
        pass

    def request_started(self) -> None:
        pass

    def add_bytes_in(self, n: int) -> None:
        pass

    def add_bytes_out(self, n: int) -> None:
        pass


NOP_ENTRY = _NopEntry()

_current = threading.local()


def current_entry():
    """The ledger entry owned by the calling worker thread, or the nop
    sink. One threading.local read — the handler-side cost of every
    lifecycle hook."""
    return getattr(_current, "entry", None) or NOP_ENTRY


class ConnEntry:
    """One accepted socket's ledger entry. Written ONLY by its owner
    (the listener thread until dispatch, then exactly one worker
    thread); /debug/connections readers take GIL-atomic snapshots of
    the plain fields, the same discipline as qprofile's in-flight
    reads."""

    __slots__ = (
        "cid", "peer", "state", "requests", "reuses",
        "bytes_in", "bytes_out", "queue_wait_s", "state_seconds",
        "opened_monotonic", "closed_total_s", "_t_last",
        "_flushed", "_flushed_reuses", "tracked",
    )

    def __init__(self, cid: int, peer: str, now: float):
        self.cid = cid
        self.peer = peer
        self.state = "accepted"
        self.requests = 0
        self.reuses = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.queue_wait_s: Optional[float] = None
        self.state_seconds: dict[str, float] = {}
        self.opened_monotonic = now
        self.closed_total_s: Optional[float] = None
        self._t_last = now
        self._flushed: dict[str, float] = {}
        self._flushed_reuses = 0
        self.tracked = True

    def transition(self, state: str) -> None:
        """Account the outgoing state's dwell and enter `state`. ONE
        clock read; plain owner-thread float math. The transition to
        ``idle`` (the request boundary) flushes aggregate deltas."""
        now = time.perf_counter()
        cur = self.state
        # lint: allow-shared-state(single-owner handoff: the listener writes only before dispatch, then exactly one worker thread owns the entry; snapshot readers take GIL-atomic reads and tolerate one stale field — the class docstring's contract)
        self.state_seconds[cur] = (
            self.state_seconds.get(cur, 0.0) + (now - self._t_last)
        )
        # lint: allow-shared-state(owner-thread-only write, same handoff contract as above)
        self._t_last = now
        # lint: allow-shared-state(owner-thread-only write, same handoff contract as above)
        self.state = state
        if state == "idle":
            self.flush()

    def request_started(self) -> None:
        self.requests += 1
        if self.requests > 1:
            # lint: allow-shared-state(owner-thread-only RMW: only the single worker thread that owns the entry runs the request loop)
            self.reuses += 1
        self.transition("executing")

    def add_bytes_in(self, n: int) -> None:
        self.bytes_in += n

    def add_bytes_out(self, n: int) -> None:
        self.bytes_out += n

    def flush(self) -> None:
        """Batch per-state second deltas (and keep-alive reuses) into
        the global counters — once per request cycle and at close, not
        per transition, so stats-lock traffic stays a single short pass
        per request."""
        for st, total in self.state_seconds.items():
            d = total - self._flushed.get(st, 0.0)
            if d > 0:
                _STATE_STATS[st].count("http_connection_state_seconds", d)
                # lint: allow-shared-state(owner-thread-only write: flush runs on the owning worker at the idle transition and at close, never concurrently)
                self._flushed[st] = total
        d = self.reuses - self._flushed_reuses
        if d > 0:
            global_stats.count("http_keepalive_reuse_total", d)
            # lint: allow-shared-state(owner-thread-only write, same flush contract as above)
            self._flushed_reuses = self.reuses

    def to_dict(self) -> dict:
        now = time.perf_counter()
        age = (
            self.closed_total_s
            if self.closed_total_s is not None
            else now - self.opened_monotonic
        )
        return {
            "id": self.cid,
            "peer": self.peer,
            "state": self.state,
            "ageSeconds": round(age, 3),
            "requests": self.requests,
            "reuses": self.reuses,
            "bytesIn": self.bytes_in,
            "bytesOut": self.bytes_out,
            "queueWaitMs": (
                round(self.queue_wait_s * 1e3, 3)
                if self.queue_wait_s is not None
                else None
            ),
            "stateSeconds": {
                s: round(v, 6) for s, v in self.state_seconds.items()
            },
        }


def parse_listen_backlogs(text: str, ports: set) -> dict:
    """{port: accept-queue depth} for LISTEN sockets on `ports`, from
    /proc/net/tcp{,6} text. For a listener the kernel reports the
    current accept backlog in the rx_queue half of tx_queue:rx_queue
    (hex); st == 0A is TCP_LISTEN. Pure function — fixture-testable."""
    out: dict = {}
    for line in text.splitlines()[1:]:
        parts = line.split()
        if len(parts) < 5 or parts[3] != "0A":
            continue
        try:
            port = int(parts[1].rsplit(":", 1)[1], 16)
            rx = int(parts[4].split(":", 1)[1], 16)
        except (ValueError, IndexError):
            continue
        if port in ports:
            out[port] = max(out.get(port, 0), rx)
    return out


def parse_listen_drops(text: str) -> Optional[tuple]:
    """(ListenOverflows, ListenDrops) from /proc/net/netstat text, or
    None when the TcpExt pair is absent/malformed. The file carries
    header/value line PAIRS per prefix (TcpExt:, IpExt:); the values
    line is the one following its own header."""
    lines = text.splitlines()
    for i, line in enumerate(lines[:-1]):
        if not line.startswith("TcpExt:"):
            continue
        nxt = lines[i + 1]
        if not nxt.startswith("TcpExt:"):
            continue
        fields = dict(zip(line.split()[1:], nxt.split()[1:]))
        try:
            return (
                int(fields["ListenOverflows"]),
                int(fields["ListenDrops"]),
            )
        except (KeyError, ValueError):
            return None
    return None


class ConnectionPlane:
    """The process-wide connection ledger: bounded live table, bounded
    ring of recently closed connections, listener registry, and the
    kernel listen-stats poller."""

    #: Live-table cap: past this, new connections still get a (metric-
    #: accruing) entry but are not TABLED — the ledger's memory stays
    #: bounded even under an fd-leak pathology. Real concurrency is
    #: bounded far lower by the fd limit.
    LIVE_CAP = 4096
    #: Recently-closed ring size.
    RING_CAP = 256

    def __init__(self, proc_net: str = "/proc/net"):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._live: dict[int, ConnEntry] = {}
        self._live_count = 0
        self._opened = 0
        self._closed: deque = deque(maxlen=self.RING_CAP)
        self._listeners: dict[int, int] = {}  # port -> refcount
        self._netstat_last: Optional[tuple] = None
        self.proc_net = proc_net

    # -- lifecycle (listener + worker threads) ------------------------------

    def register(self, peer) -> ConnEntry:
        """Called on the LISTENER thread at accept: stamps the accept
        time (the queue-wait origin) and tables the entry."""
        now = time.perf_counter()
        try:
            peer_s = f"{peer[0]}:{peer[1]}"
        except (TypeError, IndexError):
            peer_s = str(peer)
        entry = ConnEntry(next(self._ids), peer_s, now)
        # `accepted` is the registration instant itself; the dwell that
        # matters starts now, waiting for a worker + first bytes.
        entry.transition("queued")
        with self._lock:
            self._opened += 1
            self._live_count += 1
            if len(self._live) < self.LIVE_CAP:
                self._live[entry.cid] = entry
            else:
                entry.tracked = False
            live = self._live_count
        global_stats.count("http_connections_opened_total")
        global_stats.gauge("http_connections_live", live)
        return entry

    def enter(self, entry: ConnEntry) -> None:
        """Called on the WORKER thread the instant it picks the
        connection up: binds the entry to the thread and observes the
        accept-to-handler queue wait — the thread-dispatch delay."""
        wait = time.perf_counter() - entry.opened_monotonic
        entry.queue_wait_s = wait
        _current.entry = entry
        global_stats.timing("http_queue_wait_seconds", wait)

    def close_entry(self, entry: ConnEntry) -> None:
        """Worker-thread teardown: final state accounting, aggregate
        flush, move from the live table to the closed ring."""
        _current.entry = None
        entry.transition("closed")
        entry.closed_total_s = entry._t_last - entry.opened_monotonic
        entry.flush()
        with self._lock:
            self._live_count -= 1
            if entry.tracked:
                self._live.pop(entry.cid, None)
                self._closed.append(entry)
            live = self._live_count
        global_stats.gauge("http_connections_live", live)

    # -- listener registry --------------------------------------------------

    def register_listener(self, port: int) -> None:
        with self._lock:
            self._listeners[port] = self._listeners.get(port, 0) + 1

    def unregister_listener(self, port: int) -> None:
        with self._lock:
            n = self._listeners.get(port, 0) - 1
            if n <= 0:
                self._listeners.pop(port, None)
            else:
                self._listeners[port] = n

    # -- kernel truth -------------------------------------------------------

    def _read_proc(self, name: str) -> Optional[str]:
        path = os.path.join(self.proc_net, name)
        try:
            with open(path, "r") as f:
                return f.read()
        except (OSError, UnicodeDecodeError):
            return None  # non-Linux / restricted /proc: graceful no-op

    def accept_queue_depth(self, port: Optional[int] = None) -> Optional[int]:
        """Current accept-queue depth of the registered listener(s)
        (or one explicit `port`) straight from /proc/net/tcp{,6};
        None when nothing is registered or /proc is unavailable."""
        if port is not None:
            ports = {port}
        else:
            with self._lock:
                ports = set(self._listeners)
        if not ports:
            return None
        depth: Optional[int] = None
        for name in ("tcp", "tcp6"):
            text = self._read_proc(name)
            if text is None:
                continue
            for _p, rx in parse_listen_backlogs(text, ports).items():
                depth = rx if depth is None else max(depth, rx)
        return depth

    def poll_kernel(self, stats=None) -> dict:
        """One kernel-truth poll (monitor cadence + /debug/connections
        scrape): gauge the accept-queue depth, count ListenOverflows /
        ListenDrops deltas, return the current readings. Every probe
        no-ops gracefully where /proc/net is absent."""
        s = stats if stats is not None else global_stats
        out: dict = {
            "acceptQueueDepth": None,
            "listenOverflows": None,
            "listenDrops": None,
        }
        depth = self.accept_queue_depth()
        if depth is not None:
            out["acceptQueueDepth"] = depth
            s.gauge("http_accept_queue_depth", depth)
        text = self._read_proc("netstat")
        pair = parse_listen_drops(text) if text is not None else None
        if pair is not None:
            out["listenOverflows"], out["listenDrops"] = pair
            with self._lock:
                last = self._netstat_last
                self._netstat_last = pair
            if last is not None:
                d_over = pair[0] - last[0]
                d_drop = pair[1] - last[1]
                if d_over > 0:
                    s.count("http_listen_overflows_total", d_over)
                if d_drop > 0:
                    s.count("http_listen_drops_total", d_drop)
        return out

    # -- /debug/connections -------------------------------------------------

    @staticmethod
    def _reuse_bucket(reuses: int) -> str:
        if reuses == 0:
            return "0"
        if reuses < 10:
            return "1-9"
        if reuses < 100:
            return "10-99"
        return "100+"

    def snapshot(self, top: int = 50) -> dict:
        """Aggregates first (live count, per-state occupancy, reuse
        distribution, worst queue waits, kernel listen stats), then the
        newest `top` live entries and the recently-closed ring."""
        with self._lock:
            live = list(self._live.values())
            closed = list(self._closed)
            opened = self._opened
            live_count = self._live_count
        occupancy: dict[str, int] = {}
        for e in live:
            st = e.state
            occupancy[st] = occupancy.get(st, 0) + 1
        reuse_dist: dict[str, int] = {}
        for e in live + closed:
            b = self._reuse_bucket(e.reuses)
            reuse_dist[b] = reuse_dist.get(b, 0) + 1
        waits = sorted(
            (e for e in live + closed if e.queue_wait_s is not None),
            key=lambda e: e.queue_wait_s,
            reverse=True,
        )[:10]
        live.sort(key=lambda e: e.cid, reverse=True)
        closed.sort(key=lambda e: e.cid, reverse=True)
        return {
            "live": live_count,
            "opened": opened,
            "tabled": len(live),
            "stateOccupancy": occupancy,
            "reuseDistribution": reuse_dist,
            "worstQueueWaits": [
                {
                    "id": e.cid,
                    "peer": e.peer,
                    "queueWaitMs": round((e.queue_wait_s or 0.0) * 1e3, 3),
                }
                for e in waits
            ],
            "kernel": self.poll_kernel(),
            "connections": [e.to_dict() for e in live[:top]],
            "recentClosed": [e.to_dict() for e in closed[:top]],
        }


global_conn_plane = ConnectionPlane()
