"""HTTP server, API facade, wire codec, and configuration.

Keeps the reference's public HTTP surface (reference http/handler.go:274
route table) so existing Pilosa client libraries work: JSON bodies/query
strings where the reference uses them, and the protobuf wire format for
import endpoints (hand-rolled codec matching internal/public.proto field
numbers — the wire contract, not the generated code).
"""

from pilosa_tpu.server.api import API, APIError
from pilosa_tpu.server.http import Server
