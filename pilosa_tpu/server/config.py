"""Server configuration (reference server/config.go:48 Config).

Three sources, lowest to highest precedence: TOML file, environment
variables (PILOSA_TPU_*), command-line flags — same layering as the
reference's viper/pflag stack (reference docs/configuration.md:20-34).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Optional

try:  # py3.11+ stdlib; gated so a 3.10 runtime still boots servers
    import tomllib  # configured via env/flags (TOML files raise clearly)
except ModuleNotFoundError:  # pragma: no cover — interpreter-dependent
    tomllib = None


@dataclass
class ClusterConfig:
    coordinator: bool = False
    replicas: int = 1
    hosts: list[str] = field(default_factory=list)


@dataclass
class TLSConfig:
    """reference server/tlsconfig.go:1-40 + config.go:120-130: serve
    HTTPS when certificate+key are set; the internal client verifies
    peers against ca_certificate (or the system store), or skips
    verification entirely with skip_verify (self-signed dev clusters)."""

    certificate: str = ""  # PEM cert (+chain) path; empty = plain HTTP
    key: str = ""  # PEM private key path
    ca_certificate: str = ""  # PEM CA bundle for peer verification
    skip_verify: bool = False

    @property
    def enabled(self) -> bool:
        return bool(self.certificate and self.key)

    def server_context(self):
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.certificate, self.key)
        return ctx

    def client_context(self):
        """ssl context for OUTBOUND peer calls (internal client). Built
        whenever any TLS field is set — a node can be a plain-HTTP
        client of an HTTPS cluster during migration."""
        import ssl

        if self.skip_verify:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            return ctx
        return ssl.create_default_context(
            cafile=self.ca_certificate or None
        )


@dataclass
class Config:
    data_dir: str = "~/.pilosa-tpu"
    bind: str = "localhost:10101"
    executor: str = "tpu"  # tpu | cpu  (the --executor=tpu switch)
    max_writes_per_request: int = 5000
    log_path: str = ""
    verbose: bool = False
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    tls: TLSConfig = field(default_factory=TLSConfig)
    anti_entropy_interval: float = 600.0  # seconds (reference: 10m)
    metric_service: str = "memory"  # memory | none
    long_query_time: float = 0.0
    # Optional fixed Count-coalescing sleep in seconds (exec/batcher.py).
    # 0 (default) = backpressure batching: an uncontended single Count
    # dispatches immediately with no added latency, and requests arriving
    # during the in-flight device round trip coalesce into the next batch
    # (ADVICE r3: the fixed window taxed every lone query ~2 ms for no
    # batching benefit). Set >0 only to force deterministic batch windows.
    batch_window: float = 0.0
    # Pack + upload every field's HBM stack in the background at startup
    # so first queries skip the cold upload (off by default: it fronts
    # HBM residency for ALL fields, wanted only on read-serving nodes).
    preheat: bool = False
    # TCP port for jax.profiler.start_server (TensorBoard-connectable
    # device traces; the reference's profile.* config, server/config.go
    # :153-155). 0 = off. Python CPU profiling needs no config — it's
    # always-available via /debug/pprof/* (utils/profiler.py).
    profile_port: int = 0
    # Internal HTTP client timeout in seconds (peer queries, probes,
    # broadcasts). The SIGSTOP/partition tests lower it so hung-peer
    # retries happen in test time (reference Cluster.stuttering timeouts).
    client_timeout: float = 30.0
    # -- data-plane resilience (ISSUE r9) ----------------------------------
    # Default per-query deadline in seconds when the client supplies
    # neither ?timeout= nor X-Pilosa-Deadline. 0 = no default budget.
    query_timeout: float = 0.0
    # Transport-error retries for idempotent peer GETs (fragment sync,
    # probes, federation scrapes); jittered backoff between attempts.
    client_retries: int = 1
    # Per-peer circuit breaker: consecutive transport failures before the
    # breaker opens, and the base cooldown (jittered, doubling per
    # consecutive reopen up to 30x) before a half-open probe.
    breaker_threshold: int = 3
    breaker_cooldown: float = 1.0
    # Hedged shard reads: a remote scatter-gather leg silent for this
    # many seconds is re-launched at the next live replica (first result
    # wins). 0 disables hedging.
    hedge_delay: float = 0.25
    # -- cluster lifecycle (ISSUE r9) --------------------------------------
    # Follower-side resize lease in seconds: a node frozen in RESIZING
    # that hears neither a coordinator heartbeat nor a terminal status
    # for this long rolls itself back to NORMAL on the old topology
    # (the coordinator-crash escape hatch).
    resize_lease: float = 90.0
    # Concurrent fragment fetches while following a resize instruction.
    migration_concurrency: int = 2
    # Aggregate migration fetch bandwidth cap in bytes/s (0 = uncapped)
    # so a resize cannot saturate the links the serving path shares.
    migration_bandwidth: int = 0
    # -- replica consistency plane (ISSUE r15) -----------------------------
    # Bound on the read-repair probe queue (cluster/consistency.py): a
    # hedge race's two answers enqueue one background checksum diff;
    # past this depth probes are dropped (read_repair_dropped_total —
    # the periodic anti-entropy sweep backstops them) so a divergence
    # storm can never buffer unboundedly. 0 disables the monitor.
    read_repair_queue: int = 128
    # In-flight /query admission cap (server/http.py): past this many
    # concurrently executing queries, new ones are shed with 429 +
    # Retry-After + code=overloaded (http_requests_shed_total) instead
    # of queueing until the kernel RSTs the accept backlog. 0 = no cap.
    max_inflight: int = 0
    # -- write-plane backpressure (ISSUE r8) -------------------------------
    # Cap on concurrently in-flight import request bytes per node: past
    # it new /import bodies are shed with 429 + Retry-After +
    # code=import-overloaded (import_shed_total{reason=inflight-bytes})
    # instead of buffering toward OOM. A single request larger than the
    # cap is still admitted when nothing else is in flight. 0 = no cap.
    max_import_bytes: int = 0
    # Cap on the node's pending-WAL depth (un-snapshotted op records,
    # the wal_pending_ops gauge): past it imports answer 503 +
    # Retry-After + code=wal-backlog until the background snapshot
    # plane catches up. 0 = no cap.
    max_pending_wal: int = 0
    # -- read/write plane isolation (ISSUE r19) ----------------------------
    # Token-bucket cap in bytes/s on the background snapshot rewrite's
    # unlocked serialize+write middle (core/fragment.py): paces the
    # rewrite's disk pressure so a churn burst cannot saturate the I/O
    # the read plane shares. 0 = uncapped.
    snapshot_bandwidth: int = 0
    # Concurrent background snapshot rewrites across ALL fragments (the
    # global snapshot scheduler's worker-pool size). Before r19 each
    # fragment past MAX_OP_N spawned its own thread — a 64-fragment
    # churn burst meant 64 concurrent O(storage) rewrites.
    snapshot_concurrency: int = 2
    # Windowed device-refresh coalescing (exec/tpu.py): dirty shards
    # accumulate for this many milliseconds and flush as ONE incremental
    # splice round per stack, instead of every read paying the splice
    # inline after every write. Reads landing mid-window still force the
    # splice (freshness is never traded away). 0 = off (inline-only).
    refresh_window_ms: int = 0
    # SLO-adaptive ingest derating (server/api.py + utils/monitor.py):
    # when a read-latency SLO objective is burning, import admission
    # sheds a growing fraction of requests with 429 + scaled Retry-After
    # (import_derated_total{reason=read-slo}), relaxing on recovery.
    ingest_derate: bool = True
    # -- result cache (ISSUE r12) ------------------------------------------
    # Byte budget for the epoch-tagged result cache (exec/rescache.py):
    # terminal answers (Count/Row/TopN/Sum/Min/Max/GroupBy) served from
    # memory while their journal-derived epoch vector still matches.
    # 0 = disabled (matching the max-inflight convention).
    max_result_cache_bytes: int = 0
    # Bounded-staleness contract: serve a generation-mismatched cached
    # answer when every covered view is at most this many (process-
    # global) write generations behind. 0 = exact-epoch only (default).
    max_staleness: int = 0
    # Master switch: false keeps the cache out even when a byte budget
    # is set (the bench's enabled-vs-disabled same-run comparison).
    cache_enabled: bool = True
    # HBM residency budget in bytes for the TPU backend's field stacks
    # (SURVEY §7 hard part c). 0 = unbounded; over-budget fields serve
    # via row paging instead of whole-stack residency.
    max_hbm_bytes: int = 0
    # Half-life (seconds) of the HBM block-heat EWMA (ISSUE 18): how
    # fast an idle block's decayed-access-frequency heat halves. Short
    # half-lives track phase changes quickly but forget the working set
    # over a lull; the 5-minute default matches the SLO fast window.
    heat_half_life: float = 300.0
    # Shard the HBM block stacks over this many devices with a
    # jax.sharding.Mesh (parallel/mesh.py): programs run under
    # shard_map with psum/all_gather merges over ICI, replacing
    # intra-node scatter-gather (ISSUE r13). 0 = single device;
    # -1 = every visible device; N > visible devices fails boot with a
    # structured MeshConfigError rather than silently under-sharding.
    mesh_devices: int = 0
    # -- latency SLO objectives (ISSUE r10) --------------------------------
    # Each objective: {metric, quantile, threshold_s, window_s} —
    # "quantile of <metric> must stay under threshold_s seconds over
    # window_s". Evaluated from windowed histogram snapshots at
    # GET /debug/slo with fast-5m/slow-1h burn rates. TOML spelling is
    # [[slo]] tables (keys metric / quantile / threshold / window); env
    # PILOSA_TPU_SLO takes the same list as JSON.
    slo: list = field(default_factory=list)

    @staticmethod
    def _normalize_slo(entries) -> list:
        from pilosa_tpu.utils.stats import BUCKET_BOUNDS

        out = []
        for e in entries or ():
            if not isinstance(e, dict) or not e.get("metric"):
                raise ValueError(f"invalid slo objective: {e!r}")
            q = float(e.get("quantile", 0.99))
            thr = float(e.get("threshold_s", e.get("threshold", 1.0)))
            win = float(e.get("window_s", e.get("window", 3600.0)))
            # Range checks at config load, not at evaluation: `quantile
            # = 99` (the percent-vs-fraction typo) would otherwise page
            # forever with a ~1e9 burn rate instead of failing boot.
            if not 0.0 < q < 1.0:
                raise ValueError(
                    f"slo quantile must be in (0, 1), got {q!r}: {e!r}"
                )
            if thr <= 0.0:
                raise ValueError(f"slo threshold must be > 0: {e!r}")
            # The histogram's top finite bound is the largest threshold
            # the bucket CDF can evaluate: past it every observation in
            # the +Inf bucket reads as compliant and the objective can
            # never page — reject rather than silently never alert.
            if thr > BUCKET_BOUNDS[-1]:
                raise ValueError(
                    f"slo threshold {thr}s exceeds the largest histogram "
                    f"bucket bound ({BUCKET_BOUNDS[-1]:g}s): {e!r}"
                )
            if win <= 0.0:
                raise ValueError(f"slo window must be > 0: {e!r}")
            out.append(
                {
                    "metric": str(e["metric"]),
                    "quantile": q,
                    "threshold_s": thr,
                    "window_s": win,
                }
            )
        return out

    def _split_bind(self) -> tuple[str, int]:
        """Handles host:port, :port, bare host, [v6]:port, and bare IPv6."""
        b = self.bind
        if b.startswith("["):  # [::1]:10101
            host, _, rest = b[1:].partition("]")
            port = int(rest[1:]) if rest.startswith(":") and rest[1:] else 10101
            return host or "localhost", port
        if b.count(":") > 1:  # bare IPv6 address, no port
            return b, 10101
        host, _, port_s = b.partition(":")
        return host or "localhost", int(port_s) if port_s else 10101

    @property
    def host(self) -> str:
        return self._split_bind()[0]

    @property
    def port(self) -> int:
        return self._split_bind()[1]

    def to_dict(self) -> dict[str, Any]:
        return {
            "data-dir": self.data_dir,
            "bind": self.bind,
            "executor": self.executor,
            "max-writes-per-request": self.max_writes_per_request,
            "log-path": self.log_path,
            "verbose": self.verbose,
            "anti-entropy": {"interval": self.anti_entropy_interval},
            "metric": {"service": self.metric_service},
            "cluster": {
                "coordinator": self.cluster.coordinator,
                "replicas": self.cluster.replicas,
                "hosts": self.cluster.hosts,
            },
            "tls": {
                "certificate": self.tls.certificate,
                "key": self.tls.key,
                "ca-certificate": self.tls.ca_certificate,
                "skip-verify": self.tls.skip_verify,
            },
            "long-query-time": self.long_query_time,
            "client-timeout": self.client_timeout,
            "batch-window": self.batch_window,
            "preheat": self.preheat,
            "max-inflight": self.max_inflight,
            "max-import-bytes": self.max_import_bytes,
            "max-pending-wal": self.max_pending_wal,
            "snapshot-bandwidth": self.snapshot_bandwidth,
            "snapshot-concurrency": self.snapshot_concurrency,
            "refresh-window-ms": self.refresh_window_ms,
            "ingest-derate": self.ingest_derate,
            "max-hbm-bytes": self.max_hbm_bytes,
            "heat-half-life": self.heat_half_life,
            "mesh-devices": self.mesh_devices,
            "max-result-cache-bytes": self.max_result_cache_bytes,
            "max-staleness": self.max_staleness,
            "cache-enabled": self.cache_enabled,
            "profile": {"port": self.profile_port},
            "query-timeout": self.query_timeout,
            "client-retries": self.client_retries,
            "breaker-threshold": self.breaker_threshold,
            "breaker-cooldown": self.breaker_cooldown,
            "hedge-delay": self.hedge_delay,
            "resize-lease": self.resize_lease,
            "migration-concurrency": self.migration_concurrency,
            "migration-bandwidth": self.migration_bandwidth,
            "read-repair-queue": self.read_repair_queue,
            "slo": [dict(o) for o in self.slo],
        }

    @staticmethod
    def from_sources(
        toml_path: Optional[str] = None, env: Optional[dict] = None, args: Optional[dict] = None
    ) -> "Config":
        cfg = Config()
        if toml_path:
            if tomllib is None:
                raise RuntimeError(
                    "TOML config files need Python 3.11+ (tomllib); "
                    "use PILOSA_TPU_* env vars or flags on this runtime"
                )
            with open(toml_path, "rb") as f:
                data = tomllib.load(f)
            cfg._apply_toml(data)
        cfg._apply_env(env if env is not None else dict(os.environ))
        if args:
            for k, v in args.items():
                if v is not None and hasattr(cfg, k):
                    setattr(cfg, k, v)
        return cfg

    def _apply_toml(self, data: dict) -> None:
        simple = {
            "data-dir": "data_dir",
            "bind": "bind",
            "executor": "executor",
            "max-writes-per-request": "max_writes_per_request",
            "log-path": "log_path",
            "verbose": "verbose",
            "long-query-time": "long_query_time",
            "batch-window": "batch_window",
            "preheat": "preheat",
            "client-timeout": "client_timeout",
            "max-inflight": "max_inflight",
            "max-import-bytes": "max_import_bytes",
            "max-pending-wal": "max_pending_wal",
            "snapshot-bandwidth": "snapshot_bandwidth",
            "snapshot-concurrency": "snapshot_concurrency",
            "refresh-window-ms": "refresh_window_ms",
            "ingest-derate": "ingest_derate",
            "max-hbm-bytes": "max_hbm_bytes",
            "heat-half-life": "heat_half_life",
            "mesh-devices": "mesh_devices",
            "max-result-cache-bytes": "max_result_cache_bytes",
            "max-staleness": "max_staleness",
            "cache-enabled": "cache_enabled",
            "query-timeout": "query_timeout",
            "client-retries": "client_retries",
            "breaker-threshold": "breaker_threshold",
            "breaker-cooldown": "breaker_cooldown",
            "hedge-delay": "hedge_delay",
            "resize-lease": "resize_lease",
            "migration-concurrency": "migration_concurrency",
            "migration-bandwidth": "migration_bandwidth",
            "read-repair-queue": "read_repair_queue",
        }
        for k, attr in simple.items():
            if k in data:
                setattr(self, attr, data[k])
        if "profile" in data and "port" in data["profile"]:
            self.profile_port = int(data["profile"]["port"])
        if "anti-entropy" in data and "interval" in data["anti-entropy"]:
            self.anti_entropy_interval = float(data["anti-entropy"]["interval"])
        if "metric" in data and "service" in data["metric"]:
            self.metric_service = data["metric"]["service"]
        c = data.get("cluster", {})
        self.cluster.coordinator = c.get("coordinator", self.cluster.coordinator)
        self.cluster.replicas = c.get("replicas", self.cluster.replicas)
        self.cluster.hosts = c.get("hosts", self.cluster.hosts)
        t = data.get("tls", {})
        self.tls.certificate = t.get("certificate", self.tls.certificate)
        self.tls.key = t.get("key", self.tls.key)
        self.tls.ca_certificate = t.get("ca-certificate", self.tls.ca_certificate)
        self.tls.skip_verify = t.get("skip-verify", self.tls.skip_verify)
        if "slo" in data:
            self.slo = self._normalize_slo(data["slo"])

    def _apply_env(self, env: dict) -> None:
        pre = "PILOSA_TPU_"
        mapping = {
            pre + "DATA_DIR": ("data_dir", str),
            pre + "BIND": ("bind", str),
            pre + "EXECUTOR": ("executor", str),
            pre + "VERBOSE": ("verbose", lambda v: v.lower() in ("1", "true")),
            pre + "LOG_PATH": ("log_path", str),
            pre + "MAX_WRITES_PER_REQUEST": ("max_writes_per_request", int),
            pre + "LONG_QUERY_TIME": ("long_query_time", float),
            pre + "METRIC_SERVICE": ("metric_service", str),
            pre + "CLUSTER_COORDINATOR": (
                "cluster.coordinator",
                lambda v: v.lower() in ("1", "true"),
            ),
            pre + "CLUSTER_REPLICAS": ("cluster.replicas", int),
            pre + "CLUSTER_HOSTS": ("cluster.hosts", lambda v: v.split(",") if v else []),
            pre + "ANTI_ENTROPY_INTERVAL": ("anti_entropy_interval", float),
            pre + "BATCH_WINDOW": ("batch_window", float),
            pre + "PREHEAT": ("preheat", lambda v: v.lower() in ("1", "true")),
            pre + "PROFILE_PORT": ("profile_port", int),
            pre + "CLIENT_TIMEOUT": ("client_timeout", float),
            pre + "MAX_INFLIGHT": ("max_inflight", int),
            pre + "MAX_IMPORT_BYTES": ("max_import_bytes", int),
            pre + "MAX_PENDING_WAL": ("max_pending_wal", int),
            pre + "SNAPSHOT_BANDWIDTH": ("snapshot_bandwidth", int),
            pre + "SNAPSHOT_CONCURRENCY": ("snapshot_concurrency", int),
            pre + "REFRESH_WINDOW_MS": ("refresh_window_ms", int),
            pre + "INGEST_DERATE": (
                "ingest_derate",
                lambda v: v.lower() in ("1", "true"),
            ),
            pre + "MAX_HBM_BYTES": ("max_hbm_bytes", int),
            pre + "HEAT_HALF_LIFE": ("heat_half_life", float),
            pre + "MESH_DEVICES": ("mesh_devices", int),
            pre + "MAX_RESULT_CACHE_BYTES": ("max_result_cache_bytes", int),
            pre + "MAX_STALENESS": ("max_staleness", int),
            pre + "CACHE_ENABLED": (
                "cache_enabled",
                lambda v: v.lower() in ("1", "true"),
            ),
            pre + "QUERY_TIMEOUT": ("query_timeout", float),
            pre + "CLIENT_RETRIES": ("client_retries", int),
            pre + "BREAKER_THRESHOLD": ("breaker_threshold", int),
            pre + "BREAKER_COOLDOWN": ("breaker_cooldown", float),
            pre + "HEDGE_DELAY": ("hedge_delay", float),
            pre + "RESIZE_LEASE": ("resize_lease", float),
            pre + "MIGRATION_CONCURRENCY": ("migration_concurrency", int),
            pre + "MIGRATION_BANDWIDTH": ("migration_bandwidth", int),
            pre + "READ_REPAIR_QUEUE": ("read_repair_queue", int),
            pre + "SLO": (
                "slo",
                lambda v: Config._normalize_slo(json.loads(v)) if v else [],
            ),
            pre + "TLS_CERTIFICATE": ("tls.certificate", str),
            pre + "TLS_KEY": ("tls.key", str),
            pre + "TLS_CA_CERTIFICATE": ("tls.ca_certificate", str),
            pre + "TLS_SKIP_VERIFY": (
                "tls.skip_verify",
                lambda v: v.lower() in ("1", "true"),
            ),
        }
        for key, (attr, conv) in mapping.items():
            if key in env:
                value = conv(env[key])
                if "." in attr:
                    obj_name, sub = attr.split(".")
                    setattr(getattr(self, obj_name), sub, value)
                else:
                    setattr(self, attr, value)

    def toml_text(self) -> str:
        """generate-config output (reference ctl/generate_config.go)."""
        c = self
        return (
            f'data-dir = "{c.data_dir}"\n'
            f'bind = "{c.bind}"\n'
            f'executor = "{c.executor}"\n'
            f"max-writes-per-request = {c.max_writes_per_request}\n"
            f'log-path = "{c.log_path}"\n'
            f"verbose = {str(c.verbose).lower()}\n"
            f"long-query-time = {c.long_query_time}\n"
            f"batch-window = {c.batch_window}\n"
            f"preheat = {str(c.preheat).lower()}\n"
            f"client-timeout = {c.client_timeout}\n"
            f"max-inflight = {c.max_inflight}\n"
            f"max-import-bytes = {c.max_import_bytes}\n"
            f"max-pending-wal = {c.max_pending_wal}\n"
            f"snapshot-bandwidth = {c.snapshot_bandwidth}\n"
            f"snapshot-concurrency = {c.snapshot_concurrency}\n"
            f"refresh-window-ms = {c.refresh_window_ms}\n"
            f"ingest-derate = {str(c.ingest_derate).lower()}\n"
            f"max-hbm-bytes = {c.max_hbm_bytes}\n"
            f"heat-half-life = {c.heat_half_life}\n"
            f"mesh-devices = {c.mesh_devices}\n"
            f"max-result-cache-bytes = {c.max_result_cache_bytes}\n"
            f"max-staleness = {c.max_staleness}\n"
            f"cache-enabled = {str(c.cache_enabled).lower()}\n"
            f"query-timeout = {c.query_timeout}\n"
            f"client-retries = {c.client_retries}\n"
            f"breaker-threshold = {c.breaker_threshold}\n"
            f"breaker-cooldown = {c.breaker_cooldown}\n"
            f"hedge-delay = {c.hedge_delay}\n"
            f"resize-lease = {c.resize_lease}\n"
            f"migration-concurrency = {c.migration_concurrency}\n"
            f"migration-bandwidth = {c.migration_bandwidth}\n"
            f"read-repair-queue = {c.read_repair_queue}\n"
            + "".join(
                "\n[[slo]]\n"
                # json.dumps: a tagged metric spelling like
                # query_seconds{call="Count"} carries double quotes that
                # must be escaped or the emitted TOML can't round-trip.
                f'metric = {json.dumps(o["metric"])}\n'
                f"quantile = {o['quantile']}\n"
                f"threshold = {o['threshold_s']}\n"
                f"window = {o['window_s']}\n"
                for o in c.slo
            )
            + f"[profile]\nport = {c.profile_port}\n"
            "\n[tls]\n"
            f'certificate = "{c.tls.certificate}"\n'
            f'key = "{c.tls.key}"\n'
            f'ca-certificate = "{c.tls.ca_certificate}"\n'
            f"skip-verify = {str(c.tls.skip_verify).lower()}\n"
            "\n[anti-entropy]\n"
            f"interval = {c.anti_entropy_interval}\n"
            "\n[metric]\n"
            f'service = "{c.metric_service}"\n'
            "\n[cluster]\n"
            f"coordinator = {str(c.cluster.coordinator).lower()}\n"
            f"replicas = {c.cluster.replicas}\n"
            f"hosts = {c.cluster.hosts!r}\n".replace("'", '"')
        )
