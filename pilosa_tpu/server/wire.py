"""Minimal protobuf wire codec for the import/query messages.

Implements just the varint/length-delimited subset the reference's wire
contract needs (field numbers from reference internal/public.proto:57-122;
gogo-protobuf on the Go side). Hand-rolled instead of protoc-generated so
the framework stays dependency-light; the wire format is the compat
surface, not the codegen.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Iterator, Optional


def _encode_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _decode_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _iter_fields(data: bytes) -> Iterator[tuple[int, int, object]]:
    """Yields (field_number, wire_type, value)."""
    pos = 0
    while pos < len(data):
        tag, pos = _decode_varint(data, pos)
        fnum, wtype = tag >> 3, tag & 7
        if wtype == 0:  # varint
            v, pos = _decode_varint(data, pos)
            yield fnum, wtype, v
        elif wtype == 2:  # length-delimited
            ln, pos = _decode_varint(data, pos)
            yield fnum, wtype, data[pos : pos + ln]
            pos += ln
        elif wtype == 1:  # 64-bit
            yield fnum, wtype, data[pos : pos + 8]
            pos += 8
        elif wtype == 5:  # 32-bit
            yield fnum, wtype, data[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")


def _repeated_uint64(value, wtype) -> list[int]:
    """Handles both packed and unpacked repeated uint64."""
    if wtype == 0:
        return [value]
    out = []
    pos = 0
    while pos < len(value):
        v, pos = _decode_varint(value, pos)
        out.append(v)
    return out


def _zigzag_decode(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _signed(v: int) -> int:
    """int64 fields are two's-complement varints (not zigzag)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _field_str(v: bytes) -> str:
    return v.decode("utf-8")


def _encode_tag(fnum: int, wtype: int) -> bytes:
    return _encode_varint((fnum << 3) | wtype)


def _encode_string(fnum: int, s: str) -> bytes:
    b = s.encode("utf-8")
    return _encode_tag(fnum, 2) + _encode_varint(len(b)) + b


def _encode_bytes(fnum: int, b: bytes) -> bytes:
    return _encode_tag(fnum, 2) + _encode_varint(len(b)) + b


def _encode_packed_uint64(fnum: int, vals) -> bytes:
    if not len(vals):
        return b""
    body = b"".join(_encode_varint(int(v)) for v in vals)
    return _encode_tag(fnum, 2) + _encode_varint(len(body)) + body


def _encode_packed_int64(fnum: int, vals) -> bytes:
    if not len(vals):
        return b""
    body = b"".join(_encode_varint(int(v) & 0xFFFFFFFFFFFFFFFF) for v in vals)
    return _encode_tag(fnum, 2) + _encode_varint(len(body)) + body


def _encode_uint64(fnum: int, v: int) -> bytes:
    return _encode_tag(fnum, 0) + _encode_varint(v)


def _encode_bool(fnum: int, v: bool) -> bytes:
    return _encode_tag(fnum, 0) + _encode_varint(1 if v else 0)


# ---------------------------------------------------------------------------
# Messages (field numbers from reference internal/public.proto)
# ---------------------------------------------------------------------------


@dataclass
class ImportRequest:
    """reference internal/public.proto:84."""

    index: str = ""
    field: str = ""
    shard: int = 0
    row_ids: list[int] = dc_field(default_factory=list)
    column_ids: list[int] = dc_field(default_factory=list)
    row_keys: list[str] = dc_field(default_factory=list)
    column_keys: list[str] = dc_field(default_factory=list)
    timestamps: list[int] = dc_field(default_factory=list)

    def to_bytes(self) -> bytes:
        out = b""
        if self.index:
            out += _encode_string(1, self.index)
        if self.field:
            out += _encode_string(2, self.field)
        if self.shard:
            out += _encode_uint64(3, self.shard)
        out += _encode_packed_uint64(4, self.row_ids)
        out += _encode_packed_uint64(5, self.column_ids)
        out += _encode_packed_int64(6, self.timestamps)
        for k in self.row_keys:
            out += _encode_string(7, k)
        for k in self.column_keys:
            out += _encode_string(8, k)
        return out

    @staticmethod
    def from_bytes(data: bytes) -> "ImportRequest":
        m = ImportRequest()
        for fnum, wtype, v in _iter_fields(data):
            if fnum == 1:
                m.index = _field_str(v)
            elif fnum == 2:
                m.field = _field_str(v)
            elif fnum == 3:
                m.shard = v
            elif fnum == 4:
                m.row_ids.extend(_repeated_uint64(v, wtype))
            elif fnum == 5:
                m.column_ids.extend(_repeated_uint64(v, wtype))
            elif fnum == 6:
                m.timestamps.extend(_signed(x) for x in _repeated_uint64(v, wtype))
            elif fnum == 7:
                m.row_keys.append(_field_str(v))
            elif fnum == 8:
                m.column_keys.append(_field_str(v))
        return m


@dataclass
class ImportValueRequest:
    """reference internal/public.proto:95."""

    index: str = ""
    field: str = ""
    shard: int = 0
    column_ids: list[int] = dc_field(default_factory=list)
    column_keys: list[str] = dc_field(default_factory=list)
    values: list[int] = dc_field(default_factory=list)

    def to_bytes(self) -> bytes:
        out = b""
        if self.index:
            out += _encode_string(1, self.index)
        if self.field:
            out += _encode_string(2, self.field)
        if self.shard:
            out += _encode_uint64(3, self.shard)
        out += _encode_packed_uint64(5, self.column_ids)
        out += _encode_packed_int64(6, self.values)
        for k in self.column_keys:
            out += _encode_string(7, k)
        return out

    @staticmethod
    def from_bytes(data: bytes) -> "ImportValueRequest":
        m = ImportValueRequest()
        for fnum, wtype, v in _iter_fields(data):
            if fnum == 1:
                m.index = _field_str(v)
            elif fnum == 2:
                m.field = _field_str(v)
            elif fnum == 3:
                m.shard = v
            elif fnum == 5:
                m.column_ids.extend(_repeated_uint64(v, wtype))
            elif fnum == 6:
                m.values.extend(_signed(x) for x in _repeated_uint64(v, wtype))
            elif fnum == 7:
                m.column_keys.append(_field_str(v))
        return m


@dataclass
class ImportRoaringRequestView:
    name: str = ""
    data: bytes = b""


@dataclass
class ImportRoaringRequest:
    """reference internal/public.proto:119."""

    clear: bool = False
    views: list[ImportRoaringRequestView] = dc_field(default_factory=list)

    def to_bytes(self) -> bytes:
        out = b""
        if self.clear:
            out += _encode_bool(1, True)
        for v in self.views:
            body = b""
            if v.name:
                body += _encode_string(1, v.name)
            body += _encode_bytes(2, v.data)
            out += _encode_bytes(2, body)
        return out

    @staticmethod
    def from_bytes(data: bytes) -> "ImportRoaringRequest":
        m = ImportRoaringRequest()
        for fnum, wtype, v in _iter_fields(data):
            if fnum == 1:
                m.clear = bool(v)
            elif fnum == 2:
                view = ImportRoaringRequestView()
                for f2, w2, v2 in _iter_fields(v):
                    if f2 == 1:
                        view.name = _field_str(v2)
                    elif f2 == 2:
                        view.data = v2
                m.views.append(view)
        return m


@dataclass
class QueryRequest:
    """reference internal/public.proto:57."""

    query: str = ""
    shards: list[int] = dc_field(default_factory=list)
    column_attrs: bool = False
    remote: bool = False
    exclude_row_attrs: bool = False
    exclude_columns: bool = False

    def to_bytes(self) -> bytes:
        out = _encode_string(1, self.query)
        out += _encode_packed_uint64(2, self.shards)
        if self.column_attrs:
            out += _encode_bool(3, True)
        if self.remote:
            out += _encode_bool(5, True)
        if self.exclude_row_attrs:
            out += _encode_bool(6, True)
        if self.exclude_columns:
            out += _encode_bool(7, True)
        return out

    @staticmethod
    def from_bytes(data: bytes) -> "QueryRequest":
        m = QueryRequest()
        for fnum, wtype, v in _iter_fields(data):
            if fnum == 1:
                m.query = _field_str(v)
            elif fnum == 2:
                m.shards.extend(_repeated_uint64(v, wtype))
            elif fnum == 3:
                m.column_attrs = bool(v)
            elif fnum == 5:
                m.remote = bool(v)
            elif fnum == 6:
                m.exclude_row_attrs = bool(v)
            elif fnum == 7:
                m.exclude_columns = bool(v)
        return m
