"""Minimal protobuf wire codec for the import/query messages.

Implements just the varint/length-delimited subset the reference's wire
contract needs (field numbers from reference internal/public.proto:57-122;
gogo-protobuf on the Go side). Hand-rolled instead of protoc-generated so
the framework stays dependency-light; the wire format is the compat
surface, not the codegen.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Iterator, Optional

import numpy as np

from pilosa_tpu.utils.fastjson import encode_varints


def _encode_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _decode_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _iter_fields(data: bytes) -> Iterator[tuple[int, int, object]]:
    """Yields (field_number, wire_type, value)."""
    pos = 0
    while pos < len(data):
        tag, pos = _decode_varint(data, pos)
        fnum, wtype = tag >> 3, tag & 7
        if wtype == 0:  # varint
            v, pos = _decode_varint(data, pos)
            yield fnum, wtype, v
        elif wtype == 2:  # length-delimited
            ln, pos = _decode_varint(data, pos)
            yield fnum, wtype, data[pos : pos + ln]
            pos += ln
        elif wtype == 1:  # 64-bit
            yield fnum, wtype, data[pos : pos + 8]
            pos += 8
        elif wtype == 5:  # 32-bit
            yield fnum, wtype, data[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")


def _repeated_uint64(value, wtype) -> list[int]:
    """Handles both packed and unpacked repeated uint64."""
    if wtype == 0:
        return [value]
    out = []
    pos = 0
    while pos < len(value):
        v, pos = _decode_varint(value, pos)
        out.append(v)
    return out


def _zigzag_decode(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _signed(v: int) -> int:
    """int64 fields are two's-complement varints (not zigzag)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _field_str(v: bytes) -> str:
    return v.decode("utf-8")


def _encode_tag(fnum: int, wtype: int) -> bytes:
    return _encode_varint((fnum << 3) | wtype)


def _encode_string(fnum: int, s: str) -> bytes:
    b = s.encode("utf-8")
    return _encode_tag(fnum, 2) + _encode_varint(len(b)) + b


def _encode_bytes(fnum: int, b: bytes) -> bytes:
    return _encode_tag(fnum, 2) + _encode_varint(len(b)) + b


def _encode_packed_uint64(fnum: int, vals) -> bytes:
    """Packed repeated uint64. Vectorized (ISSUE r14 satellite): every
    remote shard leg's Row payload used to pay one Python varint loop
    per column; utils/fastjson.encode_varints emits identical bytes in
    a handful of numpy passes, straight from the Row columns array —
    no tolist() round trip."""
    if not len(vals):
        return b""
    body = encode_varints(np.asarray(vals, dtype=np.uint64))
    return _encode_tag(fnum, 2) + _encode_varint(len(body)) + body


def _encode_packed_int64(fnum: int, vals) -> bytes:
    """Packed repeated int64 (two's-complement varints). The uint64
    reinterpretation (& mask / .view) matches _encode_varint(v & 2^64-1)
    byte for byte."""
    if not len(vals):
        return b""
    arr = np.asarray(
        [int(v) & 0xFFFFFFFFFFFFFFFF for v in vals]
        if not isinstance(vals, np.ndarray)
        else vals.astype(np.int64).view(np.uint64),
        dtype=np.uint64,
    )
    body = encode_varints(arr)
    return _encode_tag(fnum, 2) + _encode_varint(len(body)) + body


def _encode_uint64(fnum: int, v: int) -> bytes:
    return _encode_tag(fnum, 0) + _encode_varint(v)


def _encode_bool(fnum: int, v: bool) -> bytes:
    return _encode_tag(fnum, 0) + _encode_varint(1 if v else 0)


# ---------------------------------------------------------------------------
# Messages (field numbers from reference internal/public.proto)
# ---------------------------------------------------------------------------


@dataclass
class ImportRequest:
    """reference internal/public.proto:84."""

    index: str = ""
    field: str = ""
    shard: int = 0
    row_ids: list[int] = dc_field(default_factory=list)
    column_ids: list[int] = dc_field(default_factory=list)
    row_keys: list[str] = dc_field(default_factory=list)
    column_keys: list[str] = dc_field(default_factory=list)
    timestamps: list[int] = dc_field(default_factory=list)

    def to_bytes(self) -> bytes:
        out = b""
        if self.index:
            out += _encode_string(1, self.index)
        if self.field:
            out += _encode_string(2, self.field)
        if self.shard:
            out += _encode_uint64(3, self.shard)
        out += _encode_packed_uint64(4, self.row_ids)
        out += _encode_packed_uint64(5, self.column_ids)
        out += _encode_packed_int64(6, self.timestamps)
        for k in self.row_keys:
            out += _encode_string(7, k)
        for k in self.column_keys:
            out += _encode_string(8, k)
        return out

    @staticmethod
    def from_bytes(data: bytes) -> "ImportRequest":
        m = ImportRequest()
        for fnum, wtype, v in _iter_fields(data):
            if fnum == 1:
                m.index = _field_str(v)
            elif fnum == 2:
                m.field = _field_str(v)
            elif fnum == 3:
                m.shard = v
            elif fnum == 4:
                m.row_ids.extend(_repeated_uint64(v, wtype))
            elif fnum == 5:
                m.column_ids.extend(_repeated_uint64(v, wtype))
            elif fnum == 6:
                m.timestamps.extend(_signed(x) for x in _repeated_uint64(v, wtype))
            elif fnum == 7:
                m.row_keys.append(_field_str(v))
            elif fnum == 8:
                m.column_keys.append(_field_str(v))
        return m


@dataclass
class ImportValueRequest:
    """reference internal/public.proto:95."""

    index: str = ""
    field: str = ""
    shard: int = 0
    column_ids: list[int] = dc_field(default_factory=list)
    column_keys: list[str] = dc_field(default_factory=list)
    values: list[int] = dc_field(default_factory=list)

    def to_bytes(self) -> bytes:
        out = b""
        if self.index:
            out += _encode_string(1, self.index)
        if self.field:
            out += _encode_string(2, self.field)
        if self.shard:
            out += _encode_uint64(3, self.shard)
        out += _encode_packed_uint64(5, self.column_ids)
        out += _encode_packed_int64(6, self.values)
        for k in self.column_keys:
            out += _encode_string(7, k)
        return out

    @staticmethod
    def from_bytes(data: bytes) -> "ImportValueRequest":
        m = ImportValueRequest()
        for fnum, wtype, v in _iter_fields(data):
            if fnum == 1:
                m.index = _field_str(v)
            elif fnum == 2:
                m.field = _field_str(v)
            elif fnum == 3:
                m.shard = v
            elif fnum == 5:
                m.column_ids.extend(_repeated_uint64(v, wtype))
            elif fnum == 6:
                m.values.extend(_signed(x) for x in _repeated_uint64(v, wtype))
            elif fnum == 7:
                m.column_keys.append(_field_str(v))
        return m


@dataclass
class ImportRoaringRequestView:
    name: str = ""
    data: bytes = b""


@dataclass
class ImportRoaringRequest:
    """reference internal/public.proto:119."""

    clear: bool = False
    views: list[ImportRoaringRequestView] = dc_field(default_factory=list)

    def to_bytes(self) -> bytes:
        out = b""
        if self.clear:
            out += _encode_bool(1, True)
        for v in self.views:
            body = b""
            if v.name:
                body += _encode_string(1, v.name)
            body += _encode_bytes(2, v.data)
            out += _encode_bytes(2, body)
        return out

    @staticmethod
    def from_bytes(data: bytes) -> "ImportRoaringRequest":
        m = ImportRoaringRequest()
        for fnum, wtype, v in _iter_fields(data):
            if fnum == 1:
                m.clear = bool(v)
            elif fnum == 2:
                view = ImportRoaringRequestView()
                for f2, w2, v2 in _iter_fields(v):
                    if f2 == 1:
                        view.name = _field_str(v2)
                    elif f2 == 2:
                        view.data = v2
                m.views.append(view)
        return m


# ---------------------------------------------------------------------------
# QueryResponse (reference internal/public.proto:66-81 + the type codes in
# encoding/proto/proto.go:1056-1067; attr types proto.go:1119-1124)
# ---------------------------------------------------------------------------

QUERY_RESULT_NIL = 0
QUERY_RESULT_ROW = 1
QUERY_RESULT_PAIRS = 2
QUERY_RESULT_VALCOUNT = 3
QUERY_RESULT_UINT64 = 4
QUERY_RESULT_BOOL = 5
QUERY_RESULT_ROWIDS = 6
QUERY_RESULT_GROUPCOUNTS = 7
QUERY_RESULT_ROWIDENTIFIERS = 8
QUERY_RESULT_PAIR = 9

ATTR_TYPE_STRING = 1
ATTR_TYPE_INT = 2
ATTR_TYPE_BOOL = 3
ATTR_TYPE_FLOAT = 4


def _encode_int64(fnum: int, v: int) -> bytes:
    return _encode_tag(fnum, 0) + _encode_varint(int(v) & 0xFFFFFFFFFFFFFFFF)


def _encode_attr(key: str, value) -> bytes:
    """reference internal.Attr (proto.go encodeAttrs)."""
    out = _encode_string(1, key)
    if isinstance(value, bool):
        out += _encode_uint64(2, ATTR_TYPE_BOOL) + _encode_bool(5, value)
    elif isinstance(value, int):
        out += _encode_uint64(2, ATTR_TYPE_INT) + _encode_int64(4, value)
    elif isinstance(value, float):
        import struct

        out += _encode_uint64(2, ATTR_TYPE_FLOAT)
        out += _encode_tag(6, 1) + struct.pack("<d", value)
    else:
        out += _encode_uint64(2, ATTR_TYPE_STRING) + _encode_string(3, str(value))
    return out


def _encode_attr_list(fnum: int, attrs: dict) -> bytes:
    out = b""
    for k in sorted(attrs):
        out += _encode_bytes(fnum, _encode_attr(k, attrs[k]))
    return out


def _encode_pair(p) -> bytes:
    out = b""
    if p.id:
        out += _encode_uint64(1, int(p.id))
    if p.count:
        out += _encode_uint64(2, int(p.count))
    if getattr(p, "key", ""):
        out += _encode_string(3, p.key)
    return out


def encode_query_result(r) -> bytes:
    """One executor result -> internal.QueryResult bytes (reference
    encoding/proto/proto.go:416-448 encodeQueryResult)."""
    from pilosa_tpu.core.cache import Pair
    from pilosa_tpu.core.row import Row
    from pilosa_tpu.exec.result import (
        GroupCount,
        PairField,
        PairsField,
        RowIDs,
        ValCount,
    )

    out = b""
    if isinstance(r, Row):
        # The Row columns array feeds the vectorized varint packer
        # directly — the [int(c) for c in ...tolist()] per-element loop
        # every remote shard leg used to pay is gone (ISSUE r14).
        body = _encode_packed_uint64(1, r.columns())
        if r.keys:
            for k in r.keys:
                body += _encode_string(3, k)
        if r.attrs:
            body += _encode_attr_list(2, r.attrs)
        out += _encode_tag(6, 0) + _encode_varint(QUERY_RESULT_ROW)
        out += _encode_bytes(1, body)
    elif isinstance(r, PairsField):
        out += _encode_tag(6, 0) + _encode_varint(QUERY_RESULT_PAIRS)
        for p in r.pairs:
            out += _encode_bytes(3, _encode_pair(p))
    elif isinstance(r, PairField):
        out += _encode_tag(6, 0) + _encode_varint(QUERY_RESULT_PAIR)
        out += _encode_bytes(3, _encode_pair(r.pair))
    elif isinstance(r, ValCount):
        out += _encode_tag(6, 0) + _encode_varint(QUERY_RESULT_VALCOUNT)
        body = _encode_int64(1, r.val) + _encode_int64(2, r.count)
        out += _encode_bytes(5, body)
    elif isinstance(r, bool):
        out += _encode_tag(6, 0) + _encode_varint(QUERY_RESULT_BOOL)
        out += _encode_bool(4, r)
    elif isinstance(r, int):
        out += _encode_tag(6, 0) + _encode_varint(QUERY_RESULT_UINT64)
        out += _encode_uint64(2, r)
    elif isinstance(r, RowIDs):
        out += _encode_tag(6, 0) + _encode_varint(QUERY_RESULT_ROWIDENTIFIERS)
        body = _encode_packed_uint64(1, list(r))
        for k in getattr(r, "keys", None) or []:
            body += _encode_string(2, k)
        out += _encode_bytes(9, body)
    elif isinstance(r, list) and (not r or isinstance(r[0], GroupCount)):
        out += _encode_tag(6, 0) + _encode_varint(QUERY_RESULT_GROUPCOUNTS)
        for gc in r:
            gbody = b""
            for fr in gc.group:
                fbody = _encode_string(1, fr.field)
                if fr.row_id:
                    fbody += _encode_uint64(2, int(fr.row_id))
                if getattr(fr, "row_key", ""):
                    fbody += _encode_string(3, fr.row_key)
                gbody += _encode_bytes(1, fbody)
            gbody += _encode_uint64(2, int(gc.count))
            out += _encode_bytes(8, gbody)
    else:  # None / unknown
        out += _encode_tag(6, 0) + _encode_varint(QUERY_RESULT_NIL)
    return out


def encode_query_response(results, column_attr_sets=None, err: str = "") -> bytes:
    """internal.QueryResponse (the wire shape Go client libraries read)."""
    out = b""
    if err:
        out += _encode_string(1, err)
    for r in results:
        out += _encode_bytes(2, encode_query_result(r))
    for cas in column_attr_sets or []:
        body = _encode_uint64(1, int(cas.get("id", 0)))
        if cas.get("key"):
            body += _encode_string(3, cas["key"])
        body += _encode_attr_list(2, cas.get("attrs", {}))
        out += _encode_bytes(3, body)
    return out


def _decode_attr(data: bytes):
    key, value = "", None
    typ = 0
    raw = {}
    for fnum, wtype, v in _iter_fields(data):
        raw[fnum] = v
    key = _field_str(raw.get(1, b""))
    typ = raw.get(2, 0)
    if typ == ATTR_TYPE_STRING:
        value = _field_str(raw.get(3, b""))
    elif typ == ATTR_TYPE_INT:
        value = _signed(raw.get(4, 0))
    elif typ == ATTR_TYPE_BOOL:
        value = bool(raw.get(5, 0))
    elif typ == ATTR_TYPE_FLOAT:
        import struct

        value = struct.unpack("<d", raw.get(6, b"\0" * 8))[0]
    return key, value


def decode_query_response(data: bytes) -> dict:
    """QueryResponse bytes -> plain python (for tests + python clients)."""
    results = []
    err = ""
    column_attr_sets = []
    for fnum, wtype, v in _iter_fields(data):
        if fnum == 1:
            err = _field_str(v)
        elif fnum == 2:
            results.append(_decode_query_result(v))
        elif fnum == 3:
            cas = {"id": 0, "attrs": {}}
            for f2, w2, v2 in _iter_fields(v):
                if f2 == 1:
                    cas["id"] = v2
                elif f2 == 3:
                    cas["key"] = _field_str(v2)
                elif f2 == 2:
                    k, val = _decode_attr(v2)
                    cas["attrs"][k] = val
            column_attr_sets.append(cas)
    out = {"results": results}
    if err:
        out["error"] = err
    if column_attr_sets:
        out["columnAttrSets"] = column_attr_sets
    return out


def _decode_query_result(data: bytes):
    typ = QUERY_RESULT_NIL
    fields: list[tuple[int, int, object]] = []
    for fnum, wtype, v in _iter_fields(data):
        if fnum == 6:
            typ = v
        else:
            fields.append((fnum, wtype, v))
    if typ == QUERY_RESULT_ROW:
        row = {"columns": [], "keys": [], "attrs": {}}
        for fnum, wtype, v in fields:
            if fnum == 1:
                for f2, w2, v2 in _iter_fields(v):
                    if f2 == 1:
                        row["columns"].extend(_repeated_uint64(v2, w2))
                    elif f2 == 3:
                        row["keys"].append(_field_str(v2))
                    elif f2 == 2:
                        k, val = _decode_attr(v2)
                        row["attrs"][k] = val
        if not row["keys"]:
            del row["keys"]
        return row
    if typ in (QUERY_RESULT_PAIRS, QUERY_RESULT_PAIR):
        pairs = []
        for fnum, wtype, v in fields:
            if fnum == 3:
                p = {"id": 0, "count": 0}
                for f2, w2, v2 in _iter_fields(v):
                    if f2 == 1:
                        p["id"] = v2
                    elif f2 == 2:
                        p["count"] = v2
                    elif f2 == 3:
                        p["key"] = _field_str(v2)
                pairs.append(p)
        return pairs[0] if typ == QUERY_RESULT_PAIR and pairs else pairs
    if typ == QUERY_RESULT_VALCOUNT:
        out = {"value": 0, "count": 0}
        for fnum, wtype, v in fields:
            if fnum == 5:
                for f2, w2, v2 in _iter_fields(v):
                    if f2 == 1:
                        out["value"] = _signed(v2)
                    elif f2 == 2:
                        out["count"] = _signed(v2)
        return out
    if typ == QUERY_RESULT_UINT64:
        for fnum, wtype, v in fields:
            if fnum == 2:
                return v
        return 0
    if typ == QUERY_RESULT_BOOL:
        for fnum, wtype, v in fields:
            if fnum == 4:
                return bool(v)
        return False
    if typ in (QUERY_RESULT_ROWIDS, QUERY_RESULT_ROWIDENTIFIERS):
        out = {"rows": [], "keys": []}
        for fnum, wtype, v in fields:
            if fnum == 9:
                for f2, w2, v2 in _iter_fields(v):
                    if f2 == 1:
                        out["rows"].extend(_repeated_uint64(v2, w2))
                    elif f2 == 2:
                        out["keys"].append(_field_str(v2))
        if not out["keys"]:
            del out["keys"]
        return out
    if typ == QUERY_RESULT_GROUPCOUNTS:
        groups = []
        for fnum, wtype, v in fields:
            if fnum == 8:
                gc = {"group": [], "count": 0}
                for f2, w2, v2 in _iter_fields(v):
                    if f2 == 1:
                        fr = {"field": "", "rowID": 0}
                        for f3, w3, v3 in _iter_fields(v2):
                            if f3 == 1:
                                fr["field"] = _field_str(v3)
                            elif f3 == 2:
                                fr["rowID"] = v3
                            elif f3 == 3:
                                fr["rowKey"] = _field_str(v3)
                        gc["group"].append(fr)
                    elif f2 == 2:
                        gc["count"] = v2
                groups.append(gc)
        return groups
    return None


@dataclass
class QueryRequest:
    """reference internal/public.proto:57."""

    query: str = ""
    shards: list[int] = dc_field(default_factory=list)
    column_attrs: bool = False
    remote: bool = False
    exclude_row_attrs: bool = False
    exclude_columns: bool = False

    def to_bytes(self) -> bytes:
        out = _encode_string(1, self.query)
        out += _encode_packed_uint64(2, self.shards)
        if self.column_attrs:
            out += _encode_bool(3, True)
        if self.remote:
            out += _encode_bool(5, True)
        if self.exclude_row_attrs:
            out += _encode_bool(6, True)
        if self.exclude_columns:
            out += _encode_bool(7, True)
        return out

    @staticmethod
    def from_bytes(data: bytes) -> "QueryRequest":
        m = QueryRequest()
        for fnum, wtype, v in _iter_fields(data):
            if fnum == 1:
                m.query = _field_str(v)
            elif fnum == 2:
                m.shards.extend(_repeated_uint64(v, wtype))
            elif fnum == 3:
                m.column_attrs = bool(v)
            elif fnum == 5:
                m.remote = bool(v)
            elif fnum == 6:
                m.exclude_row_attrs = bool(v)
            elif fnum == 7:
                m.exclude_columns = bool(v)
        return m
