"""HTTP layer: the reference's public route table on stdlib http.server.

Routes mirror reference http/handler.go:274-330 (public + /internal peer
endpoints). JSON in/out like the reference's handler; import endpoints
accept the protobuf wire format (Content-Type application/x-protobuf,
reference http/handler.go handlePostImport) and JSON for convenience.
"""

from __future__ import annotations

import json
import re
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import parse_qs, urlparse

from pilosa_tpu import __version__
from pilosa_tpu.utils import fastjson, threads
from pilosa_tpu.utils.qprofile import (
    ExplainPlan,
    cache_state,
    profile_scope,
)
from pilosa_tpu.utils.stats import global_stats
from pilosa_tpu.server.api import API, APIError
from pilosa_tpu.server.connplane import current_entry, global_conn_plane
from pilosa_tpu.server.wire import (
    ImportRequest,
    ImportRoaringRequest,
    ImportValueRequest,
    QueryRequest,
)

#: (method, compiled pattern, handler name, raw pattern) — the raw
#: pattern string rides along so GET /debug can render the catalogue.
_ROUTES: list[tuple[str, re.Pattern, str, str]] = []

#: RFC 7230 §3.2.6 token — the only charset a header field-name may use.
#: Validated with fullmatch so embedded whitespace, bare CR, or any other
#: separator/control char in the name is a 400, not a silent normalize.
_TOKEN_RE = re.compile(r"[!#$%&'*+\-.^_`|~0-9A-Za-z]+")

_PPROF = None
_PPROF_LOCK = threading.Lock()

#: Process start, for /debug/vars uptime — monotonic: uptime is a
#: duration, an NTP step must not dent it (lint: monotonic-time).
_START_TIME = time.monotonic()

#: Per-second cache of the RFC 7231 Date header value: rendering it
#: (email.utils.formatdate) costs more than assembling the rest of a
#: small response. Immutable (second, bytes) tuple swap — safe under
#: concurrent handler threads.
_DATE_CACHE: tuple[int, bytes] = (0, b"")


def _http_date() -> bytes:
    """Current Date header value, re-rendered at most once per second.
    Wall clock by protocol: Date is a calendar timestamp peers compare
    against their own clocks, never a duration."""
    global _DATE_CACHE
    now = int(time.time())  # lint: allow-monotonic-time(HTTP Date header is a wall-clock calendar stamp by RFC 7231)
    sec, rendered = _DATE_CACHE
    if sec != now:
        from email.utils import formatdate

        rendered = formatdate(now, usegmt=True).encode("latin-1")
        _DATE_CACHE = (now, rendered)
    return rendered


class _HTTPServer(ThreadingHTTPServer):
    """socketserver's default listen backlog is 5: under the bench's 16
    keep-alive clients plus a churn writer, a burst of reconnects (or a
    thread-scheduling stall on a one-core host) overflows it and the
    kernel RSTs the excess SYNs — the mid-window ConnectionResetError
    that zeroed BENCH_r05 (VERDICT r5 #1c). 128 matches the half of
    net.core.somaxconn actually honored everywhere."""

    request_queue_size = 128

    def __init__(self, *args, **kwargs):
        # Single-slot carry from get_request to process_request: the
        # listener thread runs one accept to completion (get_request →
        # verify_request → process_request, all sequential) before the
        # next, so no fd-keyed map is needed (ISSUE 20).
        self._pending_entry = None
        # Request-finalization barrier (ISSUE r13 satellite): the reply
        # bytes reach a same-process client one GIL slice BEFORE the
        # handler thread finishes its post-reply work (end_query,
        # profile-ring insert, span finish). Tests that read that state
        # right after a response used to poll for it; quiesce() waits
        # for it deterministically. _active counts requests from
        # dispatch entry to the END of all finalization.
        self._active_cv = threading.Condition()
        self._active = 0
        super().__init__(*args, **kwargs)

    def _request_begin(self) -> None:
        with self._active_cv:
            self._active += 1

    def _request_end(self) -> None:
        with self._active_cv:
            self._active -= 1
            if self._active <= 0:
                self._active_cv.notify_all()

    def quiesce(self, timeout: float = 5.0) -> bool:
        """Block until every request that has entered dispatch is fully
        finalized (reply sent AND post-reply bookkeeping done). True on
        drained, False on timeout. New requests arriving while waiting
        extend the wait — call from a client that has stopped sending."""
        deadline = time.monotonic() + timeout
        with self._active_cv:
            while self._active > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                # lint: allow-lock-discipline(canonical Condition.wait: it RELEASES the condition lock while blocked, handlers never stall on it)
                self._active_cv.wait(remaining)
        return True

    def get_request(self):
        """Accept + ledger registration in one breath (ISSUE 20): the
        timestamp the entry carries out of here is the origin of the
        http_queue_wait_seconds histogram — the accept-to-handler
        thread-dispatch delay the C10k front-door rewrite must
        collapse. Runs on the listener thread."""
        request, client_address = super().get_request()
        self._pending_entry = global_conn_plane.register(client_address)
        return request, client_address

    def process_request(self, request, client_address):
        """ThreadingMixIn.process_request with two changes (ISSUE 20):
        the worker starts through utils/threads.spawn — named
        http-worker-N and role-registered for the profiler,
        /debug/threads, and stall exemplars — and it runs _conn_worker,
        which binds the accept-stamped ledger entry to the worker
        before any request byte is read."""
        entry = self._pending_entry
        self._pending_entry = None
        if self.block_on_close:
            import socketserver

            vars(self).setdefault("_threads", socketserver._Threads())
        t = threads.spawn(
            "http-worker", self._conn_worker,
            args=(request, client_address, entry),
            daemon=self.daemon_threads, start=False,
        )
        self._threads.append(t)
        t.start()

    def _conn_worker(self, request, client_address, entry) -> None:
        """One connection's worker-thread body: bind the ledger entry
        (observing the queue wait), run the stock socketserver
        per-connection loop, close the entry on the way out — error
        paths included, so aborted connections still land in the
        recently-closed ring."""
        if entry is not None:
            global_conn_plane.enter(entry)
        try:
            self.process_request_thread(request, client_address)
        finally:
            if entry is not None:
                global_conn_plane.close_entry(entry)

    def server_activate(self):
        super().server_activate()
        # The kernel-truth poller matches LISTEN rows in /proc/net/tcp
        # by local port; registered here, where listen() just happened.
        global_conn_plane.register_listener(self.server_address[1])

    def server_close(self):
        try:
            global_conn_plane.unregister_listener(self.server_address[1])
        finally:
            super().server_close()

    def handle_error(self, request, client_address):
        """A client that vanishes mid-exchange can surface OUTSIDE the
        route dispatcher's abort trap (e.g. send_error during request
        parsing hitting a reset socket): count it on the same
        http_connection_aborts_total the dispatcher uses instead of
        letting socketserver spray a traceback on stderr. Anything
        that is not a connection-teardown race keeps the default noisy
        behavior — real bugs must stay loud."""
        import sys as _sys

        exc = _sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, TimeoutError)):
            from pilosa_tpu.utils.stats import global_stats

            global_stats.count("http_connection_aborts_total")
            return
        super().handle_error(request, client_address)


def _profiler():
    """Process-wide sampling profiler behind /debug/pprof/* (one server
    process = one profiler; concurrent sessions 409). Locked: two racing
    first requests must not each construct (and orphan) a sampler."""
    global _PPROF
    with _PPROF_LOCK:
        if _PPROF is None:
            from pilosa_tpu.utils.profiler import SamplingProfiler

            _PPROF = SamplingProfiler()
        return _PPROF


def _retag_prometheus(text: str, node_id: str) -> list[str]:
    """Re-tag one node's prometheus exposition with node=<id> as the
    FIRST label (federation semantics: every series in /metrics/cluster
    is attributable to its origin; series that already carry labels keep
    them). A pre-existing node= label (e.g. a member's own
    cluster_scrape_failures_total{node=...}) is renamed exported_node=
    — duplicate label names are illegal in the exposition format and
    would make Prometheus reject the whole federated scrape. Comment/
    blank lines are dropped — the merged pane re-groups series anyway.
    A histogram bucket's trailing `# {trace_id=...}` exemplar is split
    off before the value parse and re-appended after the retag."""
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        line, _, exemplar = line.partition(" # ")
        series, sep, value = line.rpartition(" ")
        if not sep:
            continue
        brace = series.find("{")
        if brace < 0:
            series = f'{series}{{node="{node_id}"}}'
        else:
            tags = series[brace + 1 :]
            # Anchored at a label-name start: a bare substring replace
            # would also mangle exported_node= on double federation.
            tags = re.sub(r'(^|,)node="', r'\1exported_node="', tags)
            series = series[: brace + 1] + f'node="{node_id}",' + tags
        suffix = f" # {exemplar}" if exemplar else ""
        out.append(f"{series} {value}{suffix}")
    return out


_HIST_LINE_RE = re.compile(
    r"^(pilosa_[A-Za-z0-9_]+)_(bucket|sum|count)\{(.*)\} ([0-9.eE+-]+)$"
)
_LE_TAG_RE = re.compile(r'(?:^|,)le="([^"]+)"')


def _merge_member_histograms(texts: list[str]) -> list[str]:
    """Sum every member's histogram series into true cluster-wide
    distributions, emitted with `node="_cluster"` as the first label
    (next to — never instead of — the per-node re-tagged series).
    Identical static bucket boundaries (utils/stats.py BUCKET_BOUNDS)
    make the cumulative bucket vectors additive per `le`, so the merged
    p99 is the quantile of the POOLED observations — the figure
    averaging per-node p99s can never produce. Only families that emit
    `_bucket` lines merge; a counter that merely ends in _count is
    untouched."""
    buckets: dict[tuple, dict[str, float]] = {}
    sums: dict[tuple, float] = {}
    counts: dict[tuple, float] = {}
    for text in texts:
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            line = line.partition(" # ")[0]  # exemplars don't merge
            m = _HIST_LINE_RE.match(line)
            if m is None:
                continue
            family, kind, tags, value = m.groups()
            try:
                v = float(value)
            except ValueError:
                continue
            if kind == "bucket":
                le = _LE_TAG_RE.search(tags)
                if le is None:
                    continue
                rest = _LE_TAG_RE.sub("", tags).strip(",")
                key = (family, rest)
                buckets.setdefault(key, {})
                buckets[key][le.group(1)] = buckets[key].get(le.group(1), 0.0) + v
            elif kind == "sum":
                sums[(family, tags)] = sums.get((family, tags), 0.0) + v
            else:
                counts[(family, tags)] = counts.get((family, tags), 0.0) + v

    def le_order(le: str) -> float:
        return float("inf") if le == "+Inf" else float(le)

    def fmt(v: float) -> str:
        # Exact, not '%g': a 6-sig-digit render of a 1,234,567-count
        # bucket would round adjacent cumulative buckets independently
        # and break monotonicity (and counter-delta math downstream).
        return str(int(v)) if v == int(v) else repr(v)

    out = []
    for (family, rest), les in sorted(buckets.items()):
        prefix = f'node="_cluster"' + ("," + rest if rest else "")
        for le in sorted(les, key=le_order):
            out.append(
                f'{family}_bucket{{{prefix},le="{le}"}} {fmt(les[le])}'
            )
        for kind, store in (("sum", sums), ("count", counts)):
            if (family, rest) in store:
                out.append(
                    f"{family}_{kind}{{{prefix}}} {fmt(store[(family, rest)])}"
                )
    return out


def route(method: str, pattern: str):
    compiled = re.compile("^" + pattern + "$")

    def deco(fn):
        _ROUTES.append((method, compiled, fn.__name__, pattern))
        return fn

    return deco


class Server:
    """Owns the API + the listening socket (reference server.go Server).

    tls: an ssl.SSLContext (or a server/config.py TLSConfig with
    certificate+key set) wraps the listener — the whole public AND
    internal route table then speaks HTTPS (reference
    server/tlsconfig.go wires one tls.Config into the http.Server)."""

    def __init__(self, api: API, host: str = "localhost", port: int = 10101,
                 tls=None):
        self.api = api
        self.host = host
        self.port = port
        if tls is not None and not hasattr(tls, "wrap_socket"):
            tls = tls.server_context() if tls.enabled else None
        self._tls = tls
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def _bind(self) -> None:
        api = self.api

        class Handler(_Handler):
            pass

        Handler.api = api
        self._httpd = _HTTPServer((self.host, self.port), Handler)
        if self._tls is not None:
            self._httpd.socket = self._tls.wrap_socket(
                self._httpd.socket, server_side=True
            )
        self.port = self._httpd.server_address[1]  # resolve port 0
        api.local_host, api.local_port = self.host, self.port
        api.local_scheme = self.scheme

    def open(self) -> "Server":
        self._bind()
        self._thread = threads.spawn(
            "http-listener", self._httpd.serve_forever
        )
        return self

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def quiesce(self, timeout: float = 5.0) -> bool:
        """Wait until every in-flight request is FULLY finalized —
        reply sent and post-reply bookkeeping (end_query, profile-ring
        insert, span finish) done. The test-visible barrier for the
        'server finalizes one GIL slice after the client has the reply
        bytes' race class (ISSUE r13 satellite; PR 10 fixed four tests
        with ad-hoc poll loops instead)."""
        if self._httpd is None:
            return True
        return self._httpd.quiesce(timeout)

    @property
    def scheme(self) -> str:
        return "https" if self._tls is not None else "http"

    @property
    def uri(self) -> str:
        return f"{self.scheme}://{self.host}:{self.port}"

    def serve_forever(self) -> None:
        """Foreground mode for the CLI."""
        self._bind()
        self._httpd.serve_forever()


class _Headers:
    """Case-insensitive header map with the one email.Message method the
    handlers use (.get). The stdlib parses request headers through
    email.feedparser — ~20% of serving CPU at the measured request rate
    — for features (obs-fold continuations, MIME structure) HTTP/1.1
    requests don't need."""

    __slots__ = ("_d", "conflicting_length", "repeated_te")

    def __init__(self):
        self._d: dict[str, str] = {}
        self.conflicting_length = False
        self.repeated_te = False

    def add(self, k: str, v: str) -> None:
        # Repeated headers keep the FIRST value, matching what
        # email.Message.get returned (comma-joining would e.g. make a
        # duplicated Content-Length unparseable downstream). DIFFERING
        # repeated Content-Length values are flagged so parse_request
        # can reject the request (RFC 7230 §3.3.2 — the classic CL.CL
        # request-smuggling vector when proxy and server disagree on
        # which value wins). ANY repeated Transfer-Encoding is flagged:
        # RFC 7230 joins them into a coding list ("chunked, gzip"),
        # so first-wins would decode chunked framing a joining proxy
        # sees differently — the TE.TE variant of the same desync class
        # (code review r7).
        lk = k.lower()
        prev = self._d.get(lk)
        if prev is None:
            self._d[lk] = v
            return
        if lk == "content-length" and prev != v:
            self.conflicting_length = True
        elif lk == "transfer-encoding":
            self.repeated_te = True

    def get(self, k: str, default=None):
        return self._d.get(k.lower(), default)


class _BadChunked(Exception):
    """Malformed/oversized chunked body: (status, reason) for the error
    reply; the connection always closes (rfile is mid-frame)."""

    def __init__(self, status: int, reason: str):
        super().__init__(reason)
        self.status = status
        self.reason = reason


class _Handler(BaseHTTPRequestHandler):
    api: API  # injected per-server subclass
    protocol_version = "HTTP/1.1"

    def handle_one_request(self):
        """Stdlib handle_one_request with the connection-plane state
        transitions woven in (ISSUE 20). The keep-alive readline blocks
        until the client's NEXT request — the transition to `reading`
        happens only AFTER it returns, so socket idle time stays
        charged to `queued`/`idle`, never to `reading`. The transition
        to `idle` at the end of a completed request is the cycle
        boundary that flushes the entry's aggregate deltas."""
        conn = current_entry()
        try:
            self.raw_requestline = self.rfile.readline(65537)
            if not self.raw_requestline:
                self.close_connection = True
                return
            conn.transition("reading")
            conn.add_bytes_in(len(self.raw_requestline))
            if len(self.raw_requestline) > 65536:
                self.requestline = ""
                self.request_version = ""
                self.command = ""
                self.send_error(414, "Request-URI Too Long")
                return
            if not self.parse_request():
                return
            conn.request_started()
            mname = "do_" + self.command
            if not hasattr(self, mname):
                self.send_error(501, f"Unsupported method ({self.command!r})")
                return
            getattr(self, mname)()
            self.wfile.flush()
            conn.transition("idle")
        except TimeoutError:
            # A read/write timed out: discard this connection (stdlib
            # semantics, minus its log_error — logging is quiet here).
            self.close_connection = True

    def parse_request(self) -> bool:
        """Minimal HTTP/1.x request parsing (mirrors the stdlib's
        semantics for request line, keep-alive, and Expect handling,
        minus email.feedparser — see _Headers). Obs-fold header
        continuations (deprecated, RFC 7230 §3.2.4) are not supported."""
        self.command = None
        self.request_version = version = self.default_request_version
        self.close_connection = True
        requestline = str(self.raw_requestline, "iso-8859-1").rstrip("\r\n")
        self.requestline = requestline
        words = requestline.split()
        if len(words) == 3:
            command, path, version = words
            if not version.startswith("HTTP/"):
                self.send_error(400, f"Bad request version ({version!r})")
                return False
            try:
                nums = version.split("/", 1)[1].split(".")
                version_number = (int(nums[0]), int(nums[1]))
                if len(nums) != 2:
                    raise ValueError
            except (ValueError, IndexError):
                self.send_error(400, f"Bad request version ({version!r})")
                return False
            if version_number >= (1, 1):
                self.close_connection = False
            if version_number >= (2, 0):
                self.send_error(505, f"Invalid HTTP version ({version!r})")
                return False
            self.request_version = version
        elif len(words) == 2:
            command, path = words
            if command != "GET":
                self.send_error(400, f"Bad HTTP/0.9 request type ({command!r})")
                return False
        elif not words:
            return False
        else:
            self.send_error(400, f"Bad request syntax ({requestline!r})")
            return False
        self.command, self.path = command, path
        headers = _Headers()
        n = 0
        head_bytes = 0  # accumulated locally: no per-line ledger calls
        while True:
            line = self.rfile.readline(65537)
            head_bytes += len(line)
            if len(line) > 65536:
                self.send_error(431, "Header line too long")
                return False
            if line in (b"\r\n", b"\n", b""):
                break
            n += 1
            if n > 100:
                self.send_error(431, "Too many headers")
                return False
            decoded = line.decode("iso-8859-1")
            # Strip ONLY the line terminator: an embedded bare CR must
            # stay visible so it fails validation below (a proxy that
            # treats it as a terminator would see different headers).
            if decoded.endswith("\r\n"):
                decoded = decoded[:-2]
            elif decoded.endswith("\n"):
                decoded = decoded[:-1]
            if decoded[:1] in (" ", "\t"):
                # Obs-fold continuation (RFC 7230 §3.2.4: reject or
                # normalize). Silently dropping it would let a folding
                # front proxy see a different header set than this
                # server — the same proxy-disagreement class as CL.CL.
                self.send_error(400, "Obsolete header folding not supported")
                return False
            if "\r" in decoded:
                self.send_error(400, "Bare CR in header line")
                return False
            k, sep, v = decoded.partition(":")
            if not sep or not _TOKEN_RE.fullmatch(k):
                # No colon, empty name, or any non-token char in the
                # field-name (whitespace before the colon included) —
                # RFC 7230 §3.2.4 requires 400, not a drop-or-normalize.
                self.send_error(400, "Malformed header line")
                return False
            if any(c < " " and c != "\t" or c == "\x7f" for c in v):
                # RFC 7230 §3.2 field-content excludes CTLs; proxies
                # disagree on NUL/VT handling (reject vs truncate) — the
                # same disagreement class as the name checks above.
                self.send_error(400, "Control character in header value")
                return False
            headers.add(k, v.strip())
        self.headers = headers
        # Header block fully read: request-head arrival (`reading`)
        # ends; validation + eager chunked decode account as `parsing`.
        conn = current_entry()
        conn.add_bytes_in(head_bytes)
        conn.transition("parsing")
        if headers.conflicting_length:
            self.send_error(400, "Conflicting Content-Length headers")
            return False
        cl = headers.get("Content-Length")
        if cl is not None and not re.fullmatch(r"[0-9]+", cl.strip()):
            # RFC 7230 §3.3.2: 1*DIGIT only. Letting "abc" or "-5"
            # through to int()/read() in _body() re-opens the keep-alive
            # desync this parser rejects for CL.CL/TE.CL (the later 500
            # would NOT close the connection, so the unread body would
            # be parsed as the next request).
            self.send_error(400, "Invalid Content-Length")
            return False
        self._chunked_body = None
        te = headers.get("Transfer-Encoding")
        if headers.repeated_te:
            self.send_error(400, "Repeated Transfer-Encoding headers")
            return False
        if te is not None:
            # Bounded chunked decoding (ISSUE r7, VERDICT r5 missing #1
            # — the reference's stdlib serves chunked clients). Anything
            # but exactly "chunked" still gets RFC 7230 §3.3.1's 501 +
            # close, and TE alongside Content-Length is the TE.CL
            # smuggling shape: reject, never pick one (§3.3.3).
            if te.strip().lower() != "chunked":
                self.send_error(501, "Transfer-Encoding not supported")
                return False
            if cl is not None:
                self.send_error(
                    400, "Transfer-Encoding with Content-Length"
                )
                return False
        conntype = (headers.get("Connection") or "").lower()
        if conntype == "close":
            self.close_connection = True
        elif conntype == "keep-alive" and self.protocol_version >= "HTTP/1.1":
            # Gate on the SERVER's protocol (stdlib semantics): an
            # HTTP/1.0 client asking keep-alive gets it.
            self.close_connection = False
        expect = (headers.get("Expect") or "").lower()
        if (
            expect == "100-continue"
            and self.protocol_version >= "HTTP/1.1"
            and self.request_version >= "HTTP/1.1"
        ):
            if not self.handle_expect_100():
                return False
        if te is not None:
            # Decode EAGERLY (after the 100-continue handshake so the
            # client has started sending): a route that never reads its
            # body must not leave chunk framing in rfile to be parsed as
            # the next request on the keep-alive connection — the same
            # desync class the old blanket 501 existed to prevent.
            try:
                self._chunked_body = self._read_chunked_body()
                # Decoded size, not wire framing bytes: the ledger's
                # bytes_in answers "how much payload", close enough.
                conn.add_bytes_in(len(self._chunked_body))
            except _BadChunked as e:
                # A malformed/oversized stream leaves rfile mid-frame:
                # the connection cannot be reused.
                self.close_connection = True
                self.send_error(e.status, e.reason)
                return False
        return True

    #: Chunked bodies are size-capped (the Content-Length path bounds
    #: itself by the declared length; chunked frames would otherwise
    #: stream without bound). 64 MiB covers any batch import the API
    #: accepts with wide margin.
    MAX_CHUNKED_BODY = 64 << 20

    def _read_chunked_body(self) -> bytes:
        """RFC 7230 §4.1 chunked-body decoder: size-capped, chunk
        extensions ignored (§4.1.1: a recipient MUST ignore unrecognized
        extensions — stdlib behavior), trailers REJECTED (nothing in
        this API consumes them, and accepting arbitrary trailing headers
        widens the smuggling surface for no capability)."""
        total = 0
        parts = []
        while True:
            line = self.rfile.readline(1026)
            if not line.endswith(b"\n") or len(line) > 1025:
                raise _BadChunked(400, "Invalid chunk size line")
            # BWS before the extension separator is grammar-legal
            # (RFC 7230 §4.1.1 chunk-ext = *( BWS ";" BWS ... )):
            # strip the token itself, not just the line.
            token = line.strip().split(b";", 1)[0].strip()
            if not re.fullmatch(rb"[0-9a-fA-F]{1,16}", token):
                raise _BadChunked(400, "Invalid chunk size")
            size = int(token, 16)
            if size == 0:
                break
            total += size
            if total > self.MAX_CHUNKED_BODY:
                raise _BadChunked(413, "Chunked body too large")
            data = self.rfile.read(size)
            if len(data) != size:
                raise _BadChunked(400, "Truncated chunk")
            if self.rfile.read(2) != b"\r\n":
                raise _BadChunked(400, "Missing chunk terminator")
            parts.append(data)
        line = self.rfile.readline(65537)
        if line not in (b"\r\n", b"\n"):
            raise _BadChunked(400, "Chunked trailers not supported")
        return b"".join(parts)
    # Headers and body go out as separate small writes; without NODELAY
    # Nagle + the peer's delayed ACK stall every keep-alive response by
    # ~40 ms — 10x the whole handling cost.
    disable_nagle_algorithm = True

    # quiet default logging
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    # -- plumbing ----------------------------------------------------------

    def _int_query(self, key: str, default: int) -> int:
        """Integer query param or a structured 400 — garbage in a debug
        URL must not surface as a PANIC 500."""
        raw = self.query.get(key)
        if raw is None:
            return default
        try:
            return int(raw)
        except ValueError:
            raise APIError(f"invalid {key}: {raw!r}") from None

    def _body(self) -> bytes:
        if getattr(self, "_chunked_body", None) is not None:
            return self._chunked_body  # decoded eagerly in parse_request
        length = int(self.headers.get("Content-Length") or 0)
        if not length:
            return b""
        data = self.rfile.read(length)
        current_entry().add_bytes_in(len(data))
        return data

    def _json_body(self) -> dict:
        return self._json_body_from(self._body())

    @staticmethod
    def _json_body_from(raw: bytes) -> dict:
        if not raw:
            return {}
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise APIError(f"invalid JSON body: {e}") from e

    def _reply(self, obj: Any, status: int = 200,
               content_type: str = "application/json",
               headers: Optional[dict] = None) -> None:
        if content_type == "application/json":
            # fastjson.dumps == json.dumps bytes (the generic fallback
            # encoder) — every JSON reply stays on one byte contract.
            data = fastjson.dumps(obj) + b"\n"
        elif isinstance(obj, bytes):
            data = obj
        else:
            data = str(obj).encode()
        self._reply_bytes(
            data, status=status, content_type=content_type, headers=headers
        )

    def _reply_bytes(self, data: bytes, status: int = 200,
                     content_type: str = "application/json",
                     headers: Optional[dict] = None) -> None:
        """Write one complete response — status line, headers, body —
        with a SINGLE wfile.write (one sendall, one TCP segment for
        small responses). The stdlib send_response/send_header path
        buffers headers but still pays a separate body write plus a
        strftime-equivalent Date render per response; this is the
        serialize-phase floor for every reply (ISSUE r14 tentpole 2).
        Semantics match send_response: Server/Date headers included,
        keep-alive framing via Content-Length, request logging elided
        (log_message is a no-op here)."""
        reason = self.responses[status][0] if status in self.responses else ""
        head = (
            f"{self.protocol_version} {status} {reason}\r\n"
            f"Server: {self.version_string()}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(data)}\r\n"
        )
        if headers:
            for k, v in headers.items():
                head += f"{k}: {v}\r\n"
        buf = head.encode("latin-1") + b"Date: " + _http_date() + b"\r\n\r\n"
        global_stats.count("http_response_payload_bytes_total", len(data))
        # `writing` brackets exactly the response send; back to
        # `executing` after — post-reply bookkeeping (span finish,
        # profile-ring insert) is handler work, not socket work.
        conn = current_entry()
        conn.transition("writing")
        self.wfile.write(buf + data)
        conn.add_bytes_out(len(buf) + len(data))
        conn.transition("executing")

    #: Machine-readable fallback `code` per status, so EVERY 4xx/5xx JSON
    #: body out of this layer carries one (ISSUE r9 satellite — the peer
    #: client already parses it, cluster/client.py) even when the raising
    #: site predates structured codes. A site-specific code always wins.
    _CODE_BY_STATUS = {
        400: "bad-request",
        404: "not-found",
        409: "conflict",
        413: "too-large",
        429: "overloaded",
        500: "internal",
        501: "not-implemented",
        502: "bad-gateway",
        503: "unavailable",
        504: "deadline-exceeded",
    }

    def _error(self, msg: str, status: int = 400, code: str = "",
               retry_after: Optional[float] = None) -> None:
        body = {
            "error": msg,
            "code": code or self._CODE_BY_STATUS.get(status, f"http-{status}"),
        }
        # 429/503/504 are retryable-by-contract: tell the client when
        # (ISSUE r9 satellite). 1 s is the breaker/hedge recovery scale;
        # a shed 429 clears as soon as an in-flight query finishes.
        # Callers with a better estimate (the ingest-derate ladder
        # scales backoff with burn persistence, ISSUE r19) override it.
        headers = (
            {"Retry-After": str(int(max(1, retry_after or 1)))}
            if status in (429, 503, 504)
            else None
        )
        self._reply(body, status=status, headers=headers)

    def _dispatch(self, method: str) -> None:
        # Finalization barrier bracket: entered before any reply byte
        # can be written, left only after ALL post-reply bookkeeping
        # (the finally blocks below included) — Server.quiesce() waits
        # on this.
        begin = getattr(self.server, "_request_begin", None)
        if begin is not None:
            begin()
        try:
            self._dispatch_inner(method)
        finally:
            end = getattr(self.server, "_request_end", None)
            if end is not None:
                end()

    def _dispatch_inner(self, method: str) -> None:
        parsed = urlparse(self.path)
        path = parsed.path
        self.query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        for m, pattern, fn_name, _raw in _ROUTES:
            if m != method:
                continue
            match = pattern.match(path)
            if match:
                # Per-route stats middleware (reference statsValidator,
                # http stats middleware in handler.go, CHANGELOG 1.4).
                from pilosa_tpu.utils.stats import global_stats
                from pilosa_tpu.utils.tracing import global_tracer

                stats = global_stats.with_tags(f"route:{fn_name[7:]}", f"method:{method}")
                stats.count("http_requests_total")
                # self.headers is an email.message.Message: its .get() is
                # case-insensitive, which matters because urllib
                # normalizes injected header casing (X-trace-id).
                span = global_tracer.start_span(
                    f"http.{fn_name}", headers=self.headers
                )
                # Origin node on the span itself: cross-node assembly
                # attributes by this tag, independent of which node's
                # ring served the span to the assembler.
                try:
                    span.set_tag("node", self._local_node_id())
                # lint: allow-except-exception(span node-tagging is best-effort display metadata)
                except Exception:  # noqa: BLE001 — tagging is best-effort
                    pass
                try:
                    with stats.timer("http_request_duration_seconds"):
                        getattr(self, fn_name)(**match.groupdict())
                except APIError as e:
                    stats.count("http_request_errors_total")
                    self._error(
                        str(e), status=e.status, code=getattr(e, "code", "")
                    )
                except (BrokenPipeError, ConnectionResetError):
                    # The client went away mid-response (or reset the
                    # socket under us). Nothing to send back — but count
                    # it: silent aborts are how BENCH_r05's mid-window
                    # reset went undiagnosed (VERDICT r5 #1c). Close the
                    # connection: a keep-alive loop would read the dead
                    # socket, raise a SECOND reset into handle_error,
                    # and double-count this one abort.
                    stats.count("http_connection_aborts_total")
                    self.close_connection = True
                except Exception as e:  # mirror the reference's panic trap
                    stats.count("http_request_errors_total")
                    self._error(f"PANIC: {e}\n{traceback.format_exc()}", status=500)
                finally:
                    span.finish()
                return
        self._error("not found", status=404)

    def do_GET(self):  # noqa: N802
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def do_DELETE(self):  # noqa: N802
        self._dispatch("DELETE")

    # -- public routes (reference http/handler.go:276-304) -----------------

    @route("GET", r"/")
    def handle_home(self):
        """Server banner: the pilosa-tpu version."""
        self._reply({"pilosa-tpu": __version__})

    @route("GET", r"/version")
    def handle_version(self):
        """Server version."""
        self._reply({"version": __version__})

    @route("GET", r"/info")
    def handle_info(self):
        """Host info: shard width, CPU count, memory."""
        self._reply(self.api.info())

    @route("GET", r"/status")
    def handle_status(self):
        """Cluster state, node list, local node id."""
        self._reply(self.api.status())

    @route("GET", r"/schema")
    def handle_get_schema(self):
        """The full index/field schema."""
        self._reply(self.api.schema())

    @route("POST", r"/schema")
    def handle_post_schema(self):
        """Apply a schema document (indexes + fields, idempotent)."""
        self.api.apply_schema(self._json_body())
        self._reply({"success": True})

    @route("GET", r"/index")
    def handle_get_indexes(self):
        self._reply(self.api.schema())

    @route("GET", r"/index/(?P<index>[^/]+)")
    def handle_get_index(self, index):
        idx = self.api.holder.index(index)
        if idx is None:
            self._error(f"index not found: {index}", status=404)
            return
        self._reply({"name": index, "options": idx.options.to_dict()})

    @route("POST", r"/index/(?P<index>[^/]+)/?")
    def handle_post_index(self, index):
        body = self._json_body()
        out = self.api.create_index(index, body.get("options", {}))
        self._reply(out)

    @route("DELETE", r"/index/(?P<index>[^/]+)")
    def handle_delete_index(self, index):
        self.api.delete_index(index)
        self._reply({"success": True})

    @route("POST", r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/?")
    def handle_post_field(self, index, field):
        body = self._json_body()
        out = self.api.create_field(index, field, body.get("options", {}))
        self._reply(out)

    @route("DELETE", r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)")
    def handle_delete_field(self, index, field):
        self.api.delete_field(index, field)
        self._reply({"success": True})

    def _request_deadline(self, use_default: bool = True):
        """The request's Deadline, or None (no budget). Precedence:
        X-Pilosa-Deadline (the internal propagation header — a remote leg
        must inherit the coordinator's remaining budget, never restart a
        full client budget), then ?timeout= (the public knob), then the
        server's query-timeout config default. Import routes pass
        use_default=False: query-timeout is sized for READ SLOs, and
        silently applying it to a long bulk import would 504 a write
        that used to complete — explicit budgets still propagate."""
        from pilosa_tpu.utils.deadline import Deadline

        raw = self.headers.get("X-Pilosa-Deadline")
        if raw is None:
            raw = self.query.get("timeout")
        if raw is not None:
            try:
                return Deadline.parse(raw)
            except ValueError:
                raise APIError(f"invalid timeout: {raw!r}") from None
        if not use_default:
            return None
        default = getattr(self.api, "query_timeout", 0.0)
        return Deadline(default) if default and default > 0 else None

    @route("POST", r"/index/(?P<index>[^/]+)/query")
    def handle_post_query(self, index):
        """Execute PQL against an index (the data-plane read path)."""
        # Admission gate FIRST (ROADMAP item 1 down payment): past the
        # configured in-flight cap the request is shed deliberately —
        # 429 + Retry-After + code=overloaded, counted — instead of
        # queueing until the accept path RSTs under burst. The unread
        # body must still be drained (chunked bodies already were, in
        # parse_request) or the keep-alive connection would parse it as
        # the next request — the desync class this file rejects
        # elsewhere.
        from pilosa_tpu.utils.stats import global_stats

        if not self.api.begin_query():
            global_stats.count("http_requests_shed_total")
            self._body()
            self._error(
                "server overloaded: in-flight query cap reached",
                status=429,
                code="overloaded",
            )
            return
        try:
            # The deadline scope opens HERE — at HTTP receipt, like the
            # query profile — so the budget covers the whole serving path
            # through response serialization (ISSUE r9 tentpole 1).
            from pilosa_tpu.utils.deadline import deadline_scope

            with deadline_scope(self._request_deadline()):
                self._serve_query(index)
        finally:
            self.api.end_query()

    def _serve_query(self, index):
        body = self._body()
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        if ctype == "application/x-protobuf":
            req = QueryRequest.from_bytes(body)
            query = req.query
            shards = req.shards or None
            column_attrs = req.column_attrs
            exclude_row_attrs = req.exclude_row_attrs
            exclude_columns = req.exclude_columns
            remote = req.remote
        else:
            try:
                query = body.decode("utf-8")
            except UnicodeDecodeError as e:
                raise APIError(f"query body is not valid UTF-8: {e}") from e
            shards = None
            if "shards" in self.query:
                shards = [int(s) for s in self.query["shards"].split(",")]
            column_attrs = self.query.get("columnAttrs") == "true"
            exclude_row_attrs = self.query.get("excludeRowAttrs") == "true"
            exclude_columns = self.query.get("excludeColumns") == "true"
            remote = self.query.get("remote") == "true"
        kw = dict(
            shards=shards,
            column_attrs=column_attrs,
            exclude_row_attrs=exclude_row_attrs,
            exclude_columns=exclude_columns,
            remote=remote,
            # Per-query result-cache bypass (docs/administration.md
            # "Result caching"): the always-fresh escape hatch. Shed
            # 429s never reach the executor, so refused queries can
            # neither hit nor populate the cache by construction.
            cache_bypass=(
                (self.headers.get("X-Pilosa-Cache") or "").strip().lower()
                == "bypass"
            ),
        )
        # Content negotiation (reference handler.go: protobuf responses
        # when the client Accepts application/x-protobuf).
        accept = (self.headers.get("Accept") or "").split(";")[0].strip()
        # The query-lifecycle profile opens HERE — at HTTP receipt — so
        # the breakdown covers the whole serving path through response
        # serialization; the executor reuses this profile (nested
        # profile_scope) and adds its phases to the same record.
        # ISSUE 16: per-query EXPLAIN opt-in. The plan allocates ONLY
        # here — with the flag off every deep-layer hook is a single
        # `getattr(prof, "explain", None)` check and the serving path
        # is byte-identical to a non-explain request.
        explain = (
            self.query.get("explain") == "1"
            or bool((self.headers.get("X-Pilosa-Explain") or "").strip())
        )
        with profile_scope(
            index=index, query=query if isinstance(query, str) else ""
        ) as prof:
            prof.remote = remote
            if explain:
                prof.explain = ExplainPlan()
            if accept == "application/x-protobuf":
                try:
                    data = self.api.query_proto(index, query, **kw)
                except APIError as e:
                    from pilosa_tpu.server.wire import encode_query_response

                    prof.error = str(e)[:200]
                    self._reply(
                        encode_query_response([], err=str(e)),
                        status=e.status,
                        content_type="application/x-protobuf",
                    )
                    return
                with prof.phase("resp_write"):
                    self._reply(
                        data, content_type="application/x-protobuf",
                        headers=self._query_headers(prof, index, remote),
                    )
                return
            # Zero-copy serving path (ISSUE r14): the API layer hands
            # back the COMPLETE response body bytes (vectorized
            # fragment encoding; cache hits splice pre-encoded wire
            # bytes), and the reply is one header+body sendall.
            data = self.api.query_bytes(index, query, **kw)
            if prof.explain is not None and data.endswith(b"}\n"):
                # Splice the executed plan into the complete body bytes
                # (the non-explain path never touches the bytes, so the
                # test_fastjson byte-identity pin is undisturbed). The
                # protobuf path above skips body attachment — its wire
                # schema is fixed — but the plan still lands in the
                # /debug/queries ring entry.
                with prof.phase("serialize"):
                    payload = json.dumps(
                        prof.explain.to_dict(), separators=(",", ":")
                    ).encode("utf-8")
                    data = data[:-2] + b',"explain":' + payload + b"}\n"
            # resp_write, not serialize: the body is already encoded
            # (query_bytes' serialize phase), and this write's wall time
            # is dominated by the GIL/scheduler handoff around the send
            # — a queueing signal, not serialization cost (the raw send
            # is ~1 µs; docs/observability.md phase table).
            with prof.phase("resp_write"):
                self._reply_bytes(
                    data, headers=self._query_headers(prof, index, remote)
                )

    def _query_headers(self, prof, index, remote) -> Optional[dict]:
        """Cache marker + (on remote legs) the view-epoch piggyback: a
        peer-issued request's response carries this node's POST-execution
        epochs for the queried index (X-Pilosa-View-Epochs), which is
        how a coordinator's per-peer epoch map advances — a replica
        write routed here invalidates the coordinator's cached fan-outs
        synchronously with its own response (ISSUE r15 tentpole 3).
        Headers stay off non-remote responses: external clients never
        pay the report bytes."""
        headers = self._cache_marker(prof)
        piggyback = self._epoch_piggyback_headers(index, remote)
        if piggyback:
            headers = dict(headers) if headers else {}
            headers.update(piggyback)
        return headers

    def _epoch_piggyback_headers(self, index, remote) -> Optional[dict]:
        """The view-epoch piggyback for any peer-issued WRITE or QUERY
        response (imports included: the freshness contract says writes
        routed through the coordinator invalidate its cached fan-outs
        synchronously with their own response, and an import that
        didn't carry its post-write epochs would leave the coordinator
        serving pre-import answers until the next ~1 s probe fold).
        None on non-remote responses: external clients never pay the
        report bytes."""
        if not remote:
            return None
        try:
            # Memoized on the generation watermark: between writes the
            # encoded report is reused, not re-walked per request.
            encoded = self.api.view_epochs_header(index)
        # lint: allow-except-exception(epoch piggyback is best-effort: its absence only delays cache invalidation to the next probe fold; the query answer itself must still ship)
        except Exception:  # noqa: BLE001 — piggyback is an optimization
            return None
        return {"X-Pilosa-View-Epochs": encoded}

    @staticmethod
    def _cache_marker(prof) -> Optional[dict]:
        """Served-from-cache response marker: X-Pilosa-Cache is `hit`
        when EVERY answer in the request came from the result cache,
        `partial` when some did (misses or uncacheable calls computed
        the rest fresh), `miss` when lookups happened but none hit, and
        `bypass` when the request asked past the cache. Absent entirely
        when no cache is wired or nothing was even looked up."""
        state = cache_state(getattr(prof, "counters", None))
        return {"X-Pilosa-Cache": state} if state else None

    #: On a shed, bodies up to this size are drained to keep the
    #: keep-alive connection framed; larger ones are NOT read (reading
    #: would buffer exactly the bytes the cap refuses) — the connection
    #: closes instead.
    SHED_DRAIN_MAX = 1 << 20

    def _import_request_bytes(self) -> int:
        """The import body size WITHOUT buffering it: the declared
        Content-Length, or the decoded chunked body when parse_request
        already read one. Known carve-out: chunked bodies are decoded
        eagerly at parse time (before the route is known), so they are
        buffered — bounded to MAX_CHUNKED_BODY (64 MiB) each — BEFORE
        the gate sees them; only Content-Length bodies are refused
        entirely unread. Documented in docs/administration.md."""
        if getattr(self, "_chunked_body", None) is not None:
            return len(self._chunked_body)
        return int(self.headers.get("Content-Length") or 0)

    def _shed_import(self, refuse, nbytes: int) -> None:
        """Answer a refused import through the _error funnel (429/503 +
        Retry-After + code) WITHOUT having buffered the body: a small
        unread body is drained to keep the keep-alive connection
        framed; a large one would be the very buffering the cap exists
        to refuse, so the connection closes after the error instead."""
        status, code, reason = refuse[:3]
        # Optional 4th element: a caller-scaled Retry-After (the
        # ingest-derate ladder deepens backoff while the read SLO
        # burns, ISSUE r19); absent, _error's fixed 1 s applies.
        retry_after = refuse[3] if len(refuse) > 3 else None
        if getattr(self, "_chunked_body", None) is None:
            if nbytes <= self.SHED_DRAIN_MAX:
                self._body()
            else:
                self.close_connection = True
        self._error(
            f"import shed ({reason}): write-side admission cap reached",
            status=status,
            code=code,
            retry_after=retry_after,
        )

    @route("POST", r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/import")
    def handle_post_import(self, index, field):
        """Bulk bit/value import (JSON or protobuf wire format)."""
        # Write-side admission FIRST (ISSUE r8 tentpole 3, the mirror of
        # handle_post_query's gate), consulted BEFORE the body is read:
        # gating after buffering would let N concurrent over-cap bodies
        # occupy RAM anyway — the OOM shape the cap refuses. The
        # deadline scope opens like the query path's so fanned-out
        # remote legs inherit the remaining budget via X-Pilosa-Deadline.
        nbytes = self._import_request_bytes()
        refuse = self.api.begin_import(nbytes)
        if refuse is not None:
            self._shed_import(refuse, nbytes)
            return
        try:
            from pilosa_tpu.utils.deadline import deadline_scope

            with deadline_scope(self._request_deadline(use_default=False)):
                self._serve_import(index, field, self._body())
        finally:
            self.api.end_import(nbytes)

    def _serve_import(self, index, field, body):
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        clear = self.query.get("clear") == "true"
        remote = self.query.get("remote") == "true"
        if ctype == "application/x-protobuf":
            # Value import is signaled by the field type on the wire level
            # in the reference client; sniff by field schema.
            idx = self.api.holder.index(index)
            f = idx.field(field) if idx else None
            if f is not None and f.options.type == "int":
                req = ImportValueRequest.from_bytes(body)
                self.api.import_values(
                    index, field, req.column_ids, req.values,
                    column_keys=req.column_keys or None, clear=clear, remote=remote,
                )
            else:
                req = ImportRequest.from_bytes(body)
                self.api.import_bits(
                    index, field, req.row_ids, req.column_ids,
                    row_keys=req.row_keys or None,
                    column_keys=req.column_keys or None,
                    timestamps=req.timestamps or None, clear=clear, remote=remote,
                )
        else:
            payload = self._json_body_from(body)
            if "values" in payload:
                self.api.import_values(
                    index, field,
                    payload.get("columnIDs", []), payload.get("values", []),
                    column_keys=payload.get("columnKeys"), clear=clear, remote=remote,
                )
            else:
                self.api.import_bits(
                    index, field,
                    payload.get("rowIDs", []), payload.get("columnIDs", []),
                    row_keys=payload.get("rowKeys"),
                    column_keys=payload.get("columnKeys"),
                    timestamps=payload.get("timestamps"), clear=clear, remote=remote,
                )
        self._reply(
            {"success": True},
            headers=self._epoch_piggyback_headers(index, remote),
        )

    @route("POST", r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/import-roaring/(?P<shard>\d+)")
    def handle_post_import_roaring(self, index, field, shard):
        nbytes = self._import_request_bytes()
        refuse = self.api.begin_import(nbytes)
        if refuse is not None:
            self._shed_import(refuse, nbytes)
            return
        try:
            from pilosa_tpu.utils.deadline import deadline_scope

            with deadline_scope(self._request_deadline(use_default=False)):
                self._serve_import_roaring(index, field, shard, self._body())
        finally:
            self.api.end_import(nbytes)

    def _serve_import_roaring(self, index, field, shard, body):
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        if ctype == "application/x-protobuf":
            req = ImportRoaringRequest.from_bytes(body)
            views = {v.name: v.data for v in req.views}
            clear = req.clear
        else:
            payload = self._json_body_from(body)
            import base64

            views = {
                k: base64.b64decode(v) for k, v in payload.get("views", {}).items()
            }
            clear = bool(payload.get("clear", False))
        remote = self.query.get("remote") == "true"
        self.api.import_roaring(index, field, int(shard), views, clear=clear, remote=remote)
        self._reply(
            {"success": True},
            headers=self._epoch_piggyback_headers(index, remote),
        )

    @route("GET", r"/export")
    def handle_get_export(self):
        index = self.query.get("index", "")
        field = self.query.get("field", "")
        shard = self.query.get("shard")  # absent = whole field, all nodes
        csv = self.api.export_csv(
            index, field, int(shard) if shard is not None else None
        )
        self._reply(csv, content_type="text/csv")

    @route("POST", r"/recalculate-caches")
    def handle_recalculate_caches(self):
        self.api.recalculate_caches()
        self._reply({"success": True})

    def _refresh_device_gauges(self) -> None:
        """Surface device-residency gauges at scrape time (HBM policy) —
        shared by /metrics and the /metrics/cluster local leg so a bare
        server (no RuntimeMonitor poller) still exports fresh values."""
        from pilosa_tpu.utils.monitor import publish_hbm_gauges
        from pilosa_tpu.utils.stats import global_stats

        backend = getattr(self.api.executor, "backend", None)
        blocks = getattr(backend, "blocks", None)
        if blocks is None:
            return
        global_stats.gauge("tpu_resident_bytes", blocks.resident_bytes())
        global_stats.gauge("tpu_stack_evictions", blocks.evictions)
        publish_hbm_gauges(blocks)

    def _exposition_reply(self, text: str) -> None:
        """Serve prometheus exposition, gating exemplars: the
        `# {trace_id=...}` suffix is OpenMetrics syntax and a text-0.0.4
        parser (stock Prometheus without exemplar scraping) reads the
        token after the value as a timestamp and fails the WHOLE scrape.
        Exemplars are kept only when the scraper opts in via
        `?exemplars=1` (the internal federation scrape, curl). The
        content type is always text-0.0.4 — never the OpenMetrics one an
        Accept header may ask for, because this exposition is NOT valid
        OpenMetrics (counter sample names carry the family's `_total`;
        a strict OM parser rejects the whole scrape as a name clash) and
        claiming the type would break exactly the scrapers it courts."""
        if self.query.get("exemplars") not in ("1", "true"):
            text = "\n".join(
                l.partition(" # ")[0] for l in text.splitlines()
            ) + "\n"
        self._reply(text, content_type="text/plain; version=0.0.4")

    @route("GET", r"/metrics")
    def handle_metrics(self):
        """Prometheus exposition of the local stats registry."""
        from pilosa_tpu.utils.stats import global_stats

        if getattr(self.api, "metric_service", "memory") == "none":
            # `[metric] service = "none"`: no exposition endpoint. The
            # registry still accrues in-process (it feeds /debug/vars
            # and the SLO evaluator) — this only closes the scrape
            # surface (config-drift rule: the knob parsed but nothing
            # consumed it).
            self._error("metrics disabled by [metric] service config",
                        status=404, code="metrics-disabled")
            return
        self._refresh_device_gauges()
        self._exposition_reply(global_stats.prometheus_text())

    @route("GET", r"/debug/queries")
    def handle_debug_queries(self):
        """Recent + in-flight queries with per-phase breakdowns (the ring
        behind pilosa_tpu/utils/qprofile.py). ?n bounds the recent list.
        The operator's first stop for 'why is THIS query slow': phases,
        version-walk counters, and errors per query, newest first. The
        `latency` block puts each recent query IN CONTEXT: per-call
        p50/p95/p99/p999 interpolated from the cumulative query_seconds
        histogram — a 40 ms query next to a 4 ms p99 is the outlier, a
        40 ms query next to a 38 ms p99 is the workload."""
        from pilosa_tpu.utils.qprofile import global_query_ring
        from pilosa_tpu.utils.stats import (
            QUANTILE_LABELS,
            bucket_quantile,
            global_stats,
        )

        n = self._int_query("n", 50)
        latency: dict[str, dict] = {}
        for name, ent in global_stats.histogram_snapshot().items():
            m = re.fullmatch(r'query_seconds\{call="([^"]+)"\}', name)
            if m is None:
                continue
            row: dict = {"count": ent["count"]}
            for label, q in QUANTILE_LABELS:
                v = bucket_quantile(ent["buckets"], q)
                row[label + "Ms"] = round(v * 1e3, 3) if v is not None else None
            latency[m.group(1)] = row
        self._reply(
            {
                "inflight": global_query_ring.inflight(),
                "recent": global_query_ring.recent(n),
                "latency": latency,
            }
        )

    @route("GET", r"/debug/slo")
    def handle_debug_slo(self):
        """SLO compliance + multi-window burn rates (utils/monitor.py
        evaluate_slos): per objective, the current windowed quantile vs
        its threshold, the fast-5m/slow-1h burn-rate pair, and trace
        exemplars from over-threshold buckets — each resolvable at
        /debug/traces/<traceID>. Objectives come from the server config
        (`slo = [{metric, quantile, threshold_s, window_s}]`); the
        answer an operator needs is "p99 query latency SLO burning 4x",
        not a page of raw series."""
        from pilosa_tpu.utils.monitor import (
            SLO_FAST_WINDOW,
            SLO_SLOW_WINDOW,
            RuntimeMonitor,
        )

        mon = getattr(self.api, "monitor", None)
        if mon is None:
            # Bare server (no CLI-started poller): a lazily attached,
            # unstarted monitor still accrues windowed snapshots on
            # every /debug/slo scrape, so burn windows fill with use.
            mon = RuntimeMonitor(self.api.holder)
            mon.slo = list(getattr(self.api, "slo", []) or [])
            self.api.monitor = mon
        objectives = mon.slo or list(getattr(self.api, "slo", []) or [])
        self._reply(
            {
                "objectives": mon.evaluate_slos(objectives),
                "fastWindowS": SLO_FAST_WINDOW,
                "slowWindowS": SLO_SLOW_WINDOW,
            }
        )

    @route("GET", r"/debug/vars")
    def handle_debug_vars(self):
        """expvar-style JSON dump of the whole stats registry (reference
        /debug/vars, http/handler.go:307): every counter/gauge/timing
        series by its prometheus series name — the greppable twin of
        /metrics for tooling that wants JSON."""
        from pilosa_tpu.utils.stats import global_stats

        out = {
            "version": __version__,
            "uptimeSeconds": round(time.monotonic() - _START_TIME, 3),
        }
        out.update(global_stats.snapshot())
        self._reply(out)

    @route("GET", r"/debug/traces")
    def handle_debug_traces(self):
        """Recent spans from the in-memory tracer (the reference exposes
        jaeger; an inspection endpoint keeps the seam observable here)."""
        from pilosa_tpu.utils.tracing import global_tracer

        n = self._int_query("n", 50)
        self._reply({"spans": global_tracer.recent(n)})

    @route("GET", r"/debug/pprof/profile")
    def handle_pprof_profile(self):
        """Go-pprof-style CPU profile (VERDICT r3 #3): sample every
        thread's stack for ?seconds (default 10), return top-N frames by
        cumulative samples. Two HTTP calls max to a hot answer; see
        utils/profiler.py for why sampling, not cProfile. ?seconds is
        hard-capped at 60 and non-numeric input is a 400 — before the
        clamp, `seconds=86400` pinned a handler thread for a day and
        garbage was a PANIC 500."""
        raw = self.query.get("seconds", "10")
        try:
            seconds = float(raw)
        except ValueError:
            raise APIError(f"invalid seconds: {raw!r}") from None
        seconds = min(max(seconds, 0.1), 60.0)
        top = self._int_query("top", 30)
        rep = _profiler().profile(seconds, top)
        if "error" in rep:
            # A manual start/stop session is active: same 409 contract as
            # the sibling endpoints, not a 200 with zero frames.
            self._error(rep["error"], status=409)
            return
        self._reply(rep)

    @route("POST", r"/debug/pprof/start")
    def handle_pprof_start(self):
        """Start a manual CPU-sampling session (409 if one is live)."""
        if _profiler().start():
            self._reply({"profiling": True})
        else:
            self._error("profiler already running", status=409)

    @route("POST", r"/debug/pprof/stop")
    def handle_pprof_stop(self):
        """Stop the manual sampling session, return top frames by role."""
        if not _profiler().running:
            self._error("profiler not running", status=409)
            return
        self._reply(_profiler().stop(self._int_query("top", 30)))

    @route("GET", r"/debug/diagnostics")
    def handle_debug_diagnostics(self):
        """Local diagnostics snapshot (reference diagnostics.go:42-260
        phone-home payload, served to the operator instead — zero
        egress)."""
        from pilosa_tpu.utils.monitor import diagnostics_snapshot

        self._reply(diagnostics_snapshot(self.api.holder))

    # -- cluster observability plane (ISSUE r8) ----------------------------

    def _local_node_id(self) -> str:
        cluster = self.api.cluster
        if cluster is not None:
            return cluster.node_id
        return f"{self.api.local_host}:{self.api.local_port}"

    def _cluster_members(self) -> list[tuple[str, object, bool]]:
        """(node_id, uri, is_local) for every cluster member, local node
        first; a single unclustered server is a one-member cluster."""
        cluster = self.api.cluster
        if cluster is None:
            return [(self._local_node_id(), None, True)]
        local_id = cluster.node_id
        out = [(local_id, None, True)]
        for n in cluster.topology.nodes:
            if n.id != local_id:
                out.append((n.id, n, False))
        return out

    def _scrape_client(self, default_timeout: float = 3.0):
        """Short-timeout client for cluster fan-outs: a downed node must
        read as a scrape failure, not hang the whole pane for the peer
        client's 30 s data-plane timeout. ?timeout= overrides (validated
        and clamped to [0.1, 30] — a garbage or zero timeout must be a
        400 / a working scrape, not a PANIC 500 or all-peers-down)."""
        from pilosa_tpu.cluster.client import InternalClient

        raw = self.query.get("timeout", default_timeout)
        try:
            timeout = float(raw)
        except ValueError:
            raise APIError(f"invalid timeout: {raw!r}") from None
        timeout = min(max(timeout, 0.1), 30.0)
        cluster = self.api.cluster
        ssl_ctx = cluster.client.ssl_context if cluster is not None else None
        return InternalClient(timeout=timeout, ssl_context=ssl_ctx)

    def _fan_out_members(self, local_fn, remote_fn):
        """Scrape every member CONCURRENTLY; returns
        [(node_id, payload | ClientError, seconds)] in member order.
        Sequential scraping would make the pane's latency the SUM of
        per-peer timeouts — with several nodes down it would go dark
        exactly when it is needed; threads bound it at ~one timeout."""
        import concurrent.futures as cf

        from pilosa_tpu.cluster.client import ClientError

        members = self._cluster_members()

        def leg(node_id, uri, is_local):
            t0 = time.perf_counter()
            try:
                out = local_fn() if is_local else remote_fn(uri)
            except ClientError as e:
                out = e
            return node_id, out, time.perf_counter() - t0

        if len(members) == 1:
            return [leg(*members[0])]
        with cf.ThreadPoolExecutor(
            max_workers=min(16, len(members))
        ) as pool:
            return [f.result() for f in
                    [pool.submit(leg, *m) for m in members]]

    @route("GET", r"/debug/traces/(?P<trace_id>[^/]+)")
    def handle_debug_trace_tree(self, trace_id):
        """Distributed trace assembly: fan out to every cluster node's
        /internal/traces/<id>, merge the spans into one parent-linked
        tree with per-node attribution, and note observed wall-clock skew
        — one slow scatter-gather leg becomes directly visible instead of
        dying in each node's local ring."""
        from pilosa_tpu.cluster.client import ClientError
        from pilosa_tpu.utils.stats import global_stats
        from pilosa_tpu.utils.tracing import global_tracer

        client = self._scrape_client()
        spans: list[dict] = []
        by_id: dict[str, dict] = {}
        failures: list[dict] = []
        legs = self._fan_out_members(
            lambda: global_tracer.spans_for(trace_id),
            lambda uri: client.node_traces(uri, trace_id),
        )
        for node_id, got, _dt in legs:
            if isinstance(got, ClientError):
                failures.append({"node": node_id, "error": str(got)})
                global_stats.with_tags(f"node:{node_id}").count(
                    "cluster_scrape_failures_total"
                )
                continue
            for s in got:
                if s["spanID"] in by_id:
                    continue  # another node's ring already held it
                # Origin attribution: a span's own node tag (set at
                # creation by the HTTP dispatcher) beats scrape origin —
                # the two only differ in in-process test clusters, where
                # the rings are shared.
                s["node"] = s.get("tags", {}).get("node", node_id)
                by_id[s["spanID"]] = s
                spans.append(s)
        children: dict[str, list] = {}
        roots = []
        max_skew = 0.0
        for s in spans:
            pid = s.get("parentID")
            parent = by_id.get(pid) if pid else None
            if parent is None:
                # Parent unknown: remote root (parent span still open or
                # aged out of its ring) — keep it as a tree root rather
                # than dropping the subtree.
                roots.append(s)
                continue
            children.setdefault(pid, []).append(s)
            if (
                parent["node"] != s["node"]
                and s.get("start") is not None
                and parent.get("start") is not None
                and s["start"] < parent["start"]
            ):
                # A child cannot start before its parent; on different
                # nodes that reads as wall-clock skew of at least this.
                max_skew = max(max_skew, parent["start"] - s["start"])

        def render(s):
            kids = sorted(
                children.get(s["spanID"], ()), key=lambda c: c.get("start") or 0
            )
            out = dict(s)
            out["children"] = [render(k) for k in kids]
            return out

        roots.sort(key=lambda s: s.get("start") or 0)
        # Attributed node set (spans' own origin), not the scrape list:
        # "which nodes did this trace touch" is the operator question.
        nodes_seen = sorted({s["node"] for s in spans})
        self._reply(
            {
                "traceID": trace_id,
                "nodes": nodes_seen,
                "spanCount": len(spans),
                "clockSkewSecondsMin": round(max_skew, 6),
                "scrapeFailures": failures,
                "tree": [render(r) for r in roots],
            }
        )

    @route("GET", r"/metrics/cluster")
    def handle_metrics_cluster(self):
        """Metrics federation: scrape every node's /metrics, re-tag each
        series with node=<id>, and append per-node scrape health
        (pilosa_cluster_scrape_up / _seconds) — one pane for the whole
        cluster; a downed node is a scrape failure, never a hang."""
        from pilosa_tpu.cluster.client import ClientError
        from pilosa_tpu.utils.stats import global_stats

        client = self._scrape_client()

        def local_text() -> str:
            self._refresh_device_gauges()
            return global_stats.prometheus_text()

        out: list[str] = []
        member_texts: list[str] = []
        for node_id, text, dt in self._fan_out_members(
            local_text, client.metrics_text
        ):
            up = 1
            if isinstance(text, ClientError):
                text = ""
                up = 0
                global_stats.with_tags(f"node:{node_id}").count(
                    "cluster_scrape_failures_total"
                )
            member_texts.append(text)
            out.extend(_retag_prometheus(text, node_id))
            out.append(f'pilosa_cluster_scrape_up{{node="{node_id}"}} {up}')
            out.append(
                f'pilosa_cluster_scrape_seconds{{node="{node_id}"}} {dt:.6f}'
            )
        # Cluster-wide latency distributions: member bucket vectors are
        # additive (shared static boundaries), so the merged series'
        # interpolated quantiles describe the pooled traffic — the
        # statistic no arithmetic on per-node p99 series can recover.
        out.extend(_merge_member_histograms(member_texts))
        self._exposition_reply("\n".join(out) + "\n")

    @route("GET", r"/debug/cluster")
    def handle_debug_cluster(self):
        """/debug/vars federation: every node's expvar-style registry
        dump keyed by node id, with per-node scrape latency/failures —
        the JSON twin of /metrics/cluster."""
        from pilosa_tpu.cluster.client import ClientError
        from pilosa_tpu.utils.stats import global_stats

        client = self._scrape_client()

        def local_vars() -> dict:
            # Same shape handle_debug_vars serves remotely: the local
            # member's entry must not be the one missing version/uptime.
            out = {
                "version": __version__,
                "uptimeSeconds": round(time.monotonic() - _START_TIME, 3),
            }
            out.update(global_stats.snapshot())
            return out

        nodes: dict[str, dict] = {}
        for node_id, got, dt in self._fan_out_members(
            local_vars, client.debug_vars
        ):
            ent: dict = {}
            if isinstance(got, ClientError):
                ent["up"] = False
                ent["error"] = str(got)
                global_stats.with_tags(f"node:{node_id}").count(
                    "cluster_scrape_failures_total"
                )
            else:
                ent["up"] = True
                ent["vars"] = got
            ent["scrapeMs"] = round(dt * 1e3, 3)
            nodes[node_id] = ent
        self._reply({"nodes": nodes})

    @route("GET", r"/debug/hbm")
    def handle_debug_hbm(self):
        """The device HBM ledger: per-entry resident bytes split by
        representation tier (dense / array-container / run-container
        source), upload epoch, access counts — sorted coldest first,
        i.e. the LRU eviction-candidate order. ?top=N truncates to the
        N coldest (0 = all, the default — back-compat with pre-r18
        consumers that expect the full ledger)."""
        backend = getattr(self.api.executor, "backend", None)
        blocks = getattr(backend, "blocks", None)
        if blocks is None or not hasattr(blocks, "ledger"):
            self._reply(
                {"residentBytes": 0, "tierBytes": {}, "evictions": 0,
                 "totalEntries": 0, "entries": []}
            )
            return
        top = self._int_query("top", 0)
        entries = blocks.ledger()
        total = len(entries)
        if top > 0:
            entries = entries[:top]
        self._reply(
            {
                "residentBytes": blocks.resident_bytes(),
                "tierBytes": blocks.tier_bytes(),
                "evictions": blocks.evictions,
                "totalEntries": total,
                "entries": entries,
            }
        )

    @route("GET", r"/debug/heat")
    def handle_debug_heat(self):
        """Block heat + miss-ratio curve (ISSUE 18): per-entry decayed-
        frequency heat (hottest first, ?top=N, default 50), the per-tier
        heat rollup behind hbm_access_heat{tier}, and the SHARDS reuse-
        distance estimator's predicted hit-rate-vs-HBM-budget curve —
        'would a bigger (or smaller) HBM budget change my hit rate', as
        a curve instead of a guess."""
        backend = getattr(self.api.executor, "backend", None)
        blocks = getattr(backend, "blocks", None)
        if blocks is None or not hasattr(blocks, "heat_snapshot"):
            self._reply(
                {"halfLifeSeconds": 0, "tierHeat": {}, "entries": [],
                 "reuse": None}
            )
            return
        top = self._int_query("top", 50)
        out = blocks.heat_snapshot(entries=top if top > 0 else -1)
        out["reuse"] = blocks.reuse.snapshot()
        self._reply(out)

    @route("GET", r"/debug/timeline")
    def handle_debug_timeline(self):
        """Interference flight recorder (ISSUE 18): second-by-second
        deltas of qps, ingest rates, per-site lock waits, snapshot
        state, device launches, and HBM residency over the trailing
        ?seconds=N window (default 60), plus pinned incidents (frozen
        automatically when an SLO objective starts burning). Each
        scrape takes a sample first, so a server without the monitor
        poller still accrues a timeline with use."""
        from pilosa_tpu.utils.monitor import global_flight_recorder

        raw = self.query.get("seconds", "60")
        try:
            seconds = min(600.0, max(1.0, float(raw)))
        except ValueError:
            raise APIError(f"invalid seconds: {raw!r}") from None
        global_flight_recorder.sample()
        self._reply(
            {
                "windowS": seconds,
                "timeline": global_flight_recorder.timeline(seconds),
                "incidents": global_flight_recorder.incidents(),
            }
        )

    @route("GET", r"/debug/workload")
    def handle_debug_workload(self):
        """Per-query-shape cost accounting (ISSUE 18): the top-K table
        of canonical-PQL shape fingerprints by cumulative device-
        seconds — which query SHAPES are spending the device, with
        bytes shipped/returned, lock-wait, and cache hit-rate per
        shape. ?top=N (default 50)."""
        from pilosa_tpu.utils.qprofile import global_workload_table

        top = self._int_query("top", 50)
        self._reply(global_workload_table.snapshot(top))

    @route("GET", r"/debug/rescache")
    def handle_debug_rescache(self):
        """The result-cache ledger (exec/rescache.py): totals plus
        entries sorted coldest-first — the LRU eviction-candidate order,
        mirroring /debug/hbm. {enabled: false} when no cache is wired."""
        rc = getattr(self.api.executor, "rescache", None)
        if rc is None:
            self._reply(
                {"enabled": False, "residentBytes": 0, "entryCount": 0,
                 "entries": []}
            )
            return
        self._reply(rc.debug_dump())

    @route("GET", r"/debug/programs")
    def handle_debug_programs(self):
        """The device-program ledger (ISSUE 16): every compiled
        executable with its (kind, build key, shape signature), compile
        cost, launch count, and cumulative post-sync device seconds —
        sorted coldest-first, mirroring /debug/hbm. A nonzero
        `recompiles` total here is the paging signal bucket-padding
        regressions show up as."""
        backend = getattr(self.api.executor, "backend", None)
        programs = getattr(backend, "programs", None)
        if programs is None or not hasattr(programs, "ledger"):
            self._reply(
                {"programs": 0, "compiles": 0, "recompiles": 0,
                 "launches": 0, "entries": []}
            )
            return
        out = programs.counts()
        out["entries"] = programs.ledger()
        self._reply(out)

    @route("GET", r"/debug/stalls")
    def handle_debug_stalls(self):
        """The lock-stall ledger (utils/locks.py): the worst recent
        contended waits across the named hot sites, worst-first, plus
        per-site aggregates. Entries carry the waiter's trace id when a
        trace was active — resolve it at /debug/traces/<id>."""
        from pilosa_tpu.utils.locks import global_stall_ledger

        n = int(self.query.get("n", "50"))
        self._reply(
            {
                "worst": global_stall_ledger.worst(n),
                "sites": global_stall_ledger.sites(),
            }
        )

    # -- connection plane (ISSUE 20) ---------------------------------------

    @route("GET", r"/debug")
    def handle_debug_index(self):
        """Route catalogue, auto-generated from the @route registry:
        every endpoint's method, path, and the first line of its
        handler docstring — the debug surface stays discoverable
        without reading source."""
        endpoints = []
        for m, _compiled, fn_name, raw in _ROUTES:
            # `(?P<index>[^/]+)` renders as `<index>` in the catalogue.
            display = re.sub(r"\(\?P<([^>]+)>[^)]*\)", r"<\1>", raw)
            display = display.replace("/?", "").replace(r"\d+", "<n>")
            doc = (getattr(type(self), fn_name).__doc__ or "").strip()
            first = doc.splitlines()[0].strip() if doc else ""
            endpoints.append(
                {"method": m, "path": display, "description": first}
            )
        endpoints.sort(key=lambda e: (e["path"], e["method"]))
        self._reply({"endpoints": endpoints})

    @route("GET", r"/debug/connections")
    def handle_debug_connections(self):
        """The connection-plane ledger (server/connplane.py): aggregates
        first — live count, per-state occupancy, keep-alive reuse
        distribution, worst queue waits, kernel accept-queue truth —
        then the newest ?top=N live and recently-closed entries."""
        top = self._int_query("top", 50)
        self._reply(global_conn_plane.snapshot(top))

    @route("GET", r"/debug/threads")
    def handle_debug_threads(self):
        """Every live thread with its registered role (utils/threads.py)
        — which plane each thread serves, with name, daemon flag, and
        age. The text twin of thread_samples_total{role}."""
        snap = threads.threads_snapshot()
        roles: dict[str, int] = {}
        for t in snap:
            roles[t["role"]] = roles.get(t["role"], 0) + 1
        self._reply({"count": len(snap), "roles": roles, "threads": snap})

    # -- internal routes (reference http/handler.go:307-318) ---------------

    @route("GET", r"/internal/traces/(?P<trace_id>[^/]+)")
    def handle_internal_traces(self, trace_id):
        """One node's local spans for a trace — the per-node leg the
        coordinator's /debug/traces/<id> assembly scrapes."""
        from pilosa_tpu.utils.tracing import global_tracer

        self._reply(
            {
                "node": self._local_node_id(),
                "spans": global_tracer.spans_for(trace_id),
            }
        )

    @route("GET", r"/internal/shards/max")
    def handle_get_shards_max(self):
        self._reply(self.api.max_shards())

    @route("GET", r"/internal/nodes")
    def handle_get_nodes(self):
        self._reply(self.api.status()["nodes"])

    @route("GET", r"/internal/fragment/nodes")
    def handle_get_fragment_nodes(self):
        index = self.query.get("index", "")
        shard = int(self.query.get("shard", "0"))
        if self.api.cluster is None:
            self._reply(self.api.status()["nodes"])
            return
        self._reply(self.api.cluster.shard_nodes_json(index, shard))

    @route("GET", r"/internal/fragment/data")
    def handle_get_fragment_data(self):
        index = self.query.get("index", "")
        field = self.query.get("field", "")
        view = self.query.get("view", "standard")
        shard = int(self.query.get("shard", "0"))
        idx = self.api.holder.index(index)
        f = idx.field(field) if idx else None
        v = f.view(view) if f else None
        frag = v.fragment(shard) if v else None
        if frag is None:
            self._error("fragment not found", status=404)
            return
        import zlib

        from pilosa_tpu.roaring import serialize

        data = serialize(frag.storage)
        # Content checksum (ISSUE r9 tentpole 2): the resize fetcher
        # verifies this before import_roaring, so a corrupt transfer is
        # retried from another source instead of silently ingested.
        self._reply(
            data,
            content_type="application/octet-stream",
            headers={
                "X-Pilosa-Content-Checksum":
                    f"crc32:{zlib.crc32(data) & 0xFFFFFFFF:08x}"
            },
        )

    @route("GET", r"/internal/fragment/blocks")
    def handle_get_fragment_blocks(self):
        index = self.query.get("index", "")
        field = self.query.get("field", "")
        view = self.query.get("view", "standard")
        shard = int(self.query.get("shard", "0"))
        idx = self.api.holder.index(index)
        f = idx.field(field) if idx else None
        v = f.view(view) if f else None
        frag = v.fragment(shard) if v else None
        if frag is None:
            self._error("fragment not found", status=404)
            return
        # (checksum, epoch) pairs since ISSUE r15: epoch 0 = unknown
        # (the receiver unions), and tombstoned blocks ship as
        # checksum 0 with their clear's epoch so block-wide deletes
        # propagate. Stringified like the checksum (64-bit-safe JSON).
        blocks = [
            {"id": b, "checksum": str(c), "epoch": str(e)}
            for b, c, e in frag.block_sums_epochs()
        ]
        self._reply({"blocks": blocks})

    @route("GET", r"/internal/fragment/block/data")
    def handle_get_fragment_block_data(self):
        index = self.query.get("index", "")
        field = self.query.get("field", "")
        view = self.query.get("view", "standard")
        shard = int(self.query.get("shard", "0"))
        block = int(self.query.get("block", "0"))
        idx = self.api.holder.index(index)
        f = idx.field(field) if idx else None
        v = f.view(view) if f else None
        frag = v.fragment(shard) if v else None
        if frag is None:
            self._error("fragment not found", status=404)
            return
        data, epoch = frag.block_data_epoch(block)
        # The epoch rides WITH the data (one lock acquisition on the
        # serving side): the syncer stamps the adopted block with the
        # epoch of exactly these bytes, not its earlier snapshot's.
        self._reply(
            data, content_type="application/octet-stream",
            headers={"X-Pilosa-Block-Epoch": str(epoch)},
        )

    @route("POST", r"/internal/fragment/repair")
    def handle_post_fragment_repair(self):
        """Targeted epoch-directed repair of one local fragment (the
        read-repair plane's fan-out, ISSUE r15 tentpole 2): this node
        pulls the named blocks from its live replicas, higher epoch
        wins, union where epochs are unknown. Body: {index, field,
        view, shard, blocks: [...]} — an empty blocks list repairs the
        whole fragment."""
        if self.api.cluster is None:
            self._error("not clustered", status=400)
            return
        body = self._json_body()
        from pilosa_tpu.cluster.sync import HolderSyncer

        repaired = HolderSyncer(self.api.cluster).sync_fragment_targeted(
            str(body.get("index", "")),
            str(body.get("field", "")),
            str(body.get("view", "standard")),
            int(body.get("shard", 0)),
            blocks=[int(b) for b in body.get("blocks", [])],
        )
        self._reply({"repaired": repaired})

    @route("GET", r"/debug/consistency")
    def handle_debug_consistency(self):
        """Replica-divergence ledger (ISSUE r15 tentpole 2), ordered by
        staleness — unrepaired divergences first, oldest first. {enabled:
        false} when no divergence monitor is wired."""
        mon = getattr(self.api.cluster, "divergence", None) if (
            self.api.cluster is not None
        ) else None
        if mon is None:
            self._reply(
                {"enabled": False, "pendingProbes": 0, "entries": []}
            )
            return
        self._reply(mon.debug_dump())

    @route("GET", r"/internal/field/state")
    def handle_get_field_state(self):
        """View names + available shards for one field (anti-entropy and
        resize discovery; the reference ships this in NodeStatus gossip)."""
        index = self.query.get("index", "")
        field = self.query.get("field", "")
        idx = self.api.holder.index(index)
        f = idx.field(field) if idx else None
        if f is None:
            self._error(f"field not found: {field}", status=404)
            return
        self._reply(
            {
                "views": sorted(f.views),
                # lint: allow-hot-serialize(debug route over the schema-sized shard inventory)
                "availableShards": f.available_shards().to_array().tolist(),
            }
        )

    @route("GET", r"/internal/attr/blocks")
    def handle_get_attr_blocks(self):
        store = self._attr_store()
        if store is None:
            return
        self._reply(
            {"blocks": [{"id": b, "checksum": str(c)} for b, c in store.blocks()]}
        )

    @route("GET", r"/internal/attr/block/data")
    def handle_get_attr_block_data(self):
        store = self._attr_store()
        if store is None:
            return
        block = int(self.query.get("block", "0"))
        self._reply({"attrs": {str(k): v for k, v in store.block_data(block).items()}})

    def _attr_store(self):
        index = self.query.get("index", "")
        field = self.query.get("field", "")
        idx = self.api.holder.index(index)
        if idx is None:
            self._error(f"index not found: {index}", status=404)
            return None
        if field:
            f = idx.field(field)
            store = f.row_attr_store if f else None
        else:
            store = idx.column_attr_store
        if store is None:
            self._error("no attr store", status=400)
            return None
        return store

    # -- resize control (reference api.go:1193-1261) -----------------------

    @route("POST", r"/cluster/resize/add-node")
    def handle_resize_add_node(self):
        body = self._json_body()
        self._reply(self.api.resize_add_node(body))

    @route("POST", r"/cluster/resize/remove-node")
    def handle_resize_remove_node(self):
        body = self._json_body()
        self._reply(self.api.resize_remove_node(body.get("id", "")))

    @route("POST", r"/cluster/resize/abort")
    def handle_resize_abort(self):
        self.api.resize_abort()
        self._reply({"success": True})

    @route("POST", r"/cluster/coordinator")
    def handle_set_coordinator(self):
        body = self._json_body()
        self._reply(self.api.set_coordinator(body.get("id", "")))

    @route("POST", r"/internal/cluster/message")
    def handle_post_cluster_message(self):
        if self.api.cluster is None:
            self._error("not clustered", status=400)
            return
        from pilosa_tpu.cluster.broadcast import Message

        body = self._body()
        try:
            msg = Message.from_bytes(body)
        # lint: allow-except-exception(delivered as the structured bad-frame 400 the sender's wire renegotiation keys on)
        except Exception:
            # Structured parse-failure code BEFORE any side effect: the
            # sender's wire negotiation (broadcast.py _deliver) retries
            # with legacy JSON on exactly this; handler errors below keep
            # the generic panic trap and are never retried.
            self._error("unparseable control frame", status=400, code="bad-frame")
            return
        self.api.cluster.apply_message(msg)
        self._reply({"success": True})

    @route("POST", r"/internal/translate/keys")
    def handle_post_translate_keys(self):
        body = self._json_body()
        index = body.get("index", "")
        field = body.get("field", "")
        keys = body.get("keys", [])
        idx = self.api.holder.index(index)
        if idx is None:
            self._error(f"index not found: {index}", status=404)
            return
        if field:
            f = idx.field(field)
            store = f.translate_store if f else None
        else:
            store = idx.translate_store
        if store is None:
            self._error("no translate store", status=400)
            return
        self._reply({"ids": store.translate_keys(keys)})

    @route("GET", r"/internal/translate/data")
    def handle_get_translate_data(self):
        index = self.query.get("index", "")
        field = self.query.get("field", "")
        since = int(self.query.get("offset", "0"))
        idx = self.api.holder.index(index)
        if idx is None:
            self._error(f"index not found: {index}", status=404)
            return
        store = idx.translate_store
        if field:
            f = idx.field(field)
            store = f.translate_store if f else None
        if store is None:
            self._error("no translate store", status=400)
            return
        self._reply({"entries": store.entries_since(since)})
