"""API facade (reference api.go:42).

Sits between the HTTP handler and the holder/executor/cluster: validates
cluster state per method (reference api.go:119 apiMethod validation),
performs import-side key translation and existence tracking, and exposes
schema CRUD. The cluster attribute is None in single-node mode; the
cluster layer injects itself to gate methods and route imports.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Optional

import numpy as np

from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import IndexOptions
from pilosa_tpu.core.timequantum import parse_time
from pilosa_tpu.exec import ExecOptions, Executor
from pilosa_tpu.exec.cpu import QueryError
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils.deadline import Deadline, deadline_scope


class APIError(Exception):
    def __init__(self, msg: str, status: int = 400, code: str = ""):
        super().__init__(msg)
        self.status = status
        # Machine-readable error class carried in the JSON body (additive
        # — the HTTP status stays reference-compatible). "not-found" lets
        # the cluster's missed-DDL repair distinguish a genuinely absent
        # index/field from a peer that lacks schema, without string
        # matching (ADVICE r2 #4).
        self.code = code


class NotFoundError(APIError):
    def __init__(self, msg: str):
        super().__init__(msg, status=404, code="not-found")


class ConflictError(APIError):
    def __init__(self, msg: str):
        super().__init__(msg, status=409)


# Methods allowed in non-NORMAL cluster states (reference api.go:1343+).
_STATE_EXEMPT = {"Status", "ClusterMessage", "ResizeAbort", "SetCoordinator"}


class API:
    def __init__(self, holder: Holder, executor: Optional[Executor] = None, cluster=None):
        self.holder = holder
        self.executor = executor if executor is not None else Executor(holder)
        self.cluster = cluster  # wired by pilosa_tpu/cluster
        # Memo for the encoded X-Pilosa-View-Epochs header: a bounded
        # tuple of (index, generation watermark at build, encoded
        # payload) entries so a coordinator serving remote legs for
        # several indexes doesn't thrash one slot. Rebuilt only when
        # ANY view/field minted since — between writes every remote leg
        # reuses the bytes instead of re-walking the schema +
        # re-encoding per request. An immutable tuple published by
        # plain assignment (the documented GIL-atomic swap idiom), so
        # concurrent query threads need no lock.
        self._epoch_header_memo: tuple = ()
        # Same memo for /status's ALL-index indexEpochs report (the
        # failure detector probes every peer ~1/s: between mints the
        # probe plane reuses the walk instead of re-paying it per probe
        # per peer). The memoized subtree is shared across responses —
        # consumers read it, never mutate. A schema object created
        # without a mint (bare field, no view yet) shows up one mint
        # late; that only delays a peer's cacheability (unknown field =
        # uncacheable), never serves stale.
        self._epoch_status_memo: tuple = (-1, None)
        # Set by the HTTP server once the listener is bound.
        self.local_host = "localhost"
        self.local_port = 10101
        self.local_scheme = "http"
        # Default per-query deadline in seconds when the client supplies
        # neither ?timeout= nor X-Pilosa-Deadline (config query-timeout).
        # 0 = no default budget.
        self.query_timeout = 0.0
        # SLO objectives ([{metric, quantile, threshold_s, window_s}],
        # config `slo`) and the RuntimeMonitor whose windowed histogram
        # snapshots /debug/slo evaluates them against. The CLI wires
        # both; a bare server lazily attaches an unstarted monitor on
        # first /debug/slo scrape.
        self.slo: list[dict] = []
        self.monitor = None
        # Deliberate load shedding (ROADMAP item 1 down payment): when
        # max_inflight_queries > 0, the HTTP layer admits at most that
        # many concurrent /query executions and answers the rest with
        # 429 + Retry-After + code=overloaded — the front door degrades
        # by contract, never by kernel reset. 0 = unbounded (default).
        self.max_inflight_queries = 0
        self._inflight_lock = threading.Lock()
        self._inflight_queries = 0
        # Write-side admission (ISSUE r8 tentpole 3, mirroring the read
        # gate above): bounded in-flight import bytes + a pending-WAL
        # depth cap. Over either, imports are shed deliberately
        # (429/503 + Retry-After + code) — the node degrades by
        # contract, never by OOM. 0 = unbounded (defaults).
        self.max_import_bytes = 0
        self.max_pending_wal = 0
        self._import_lock = threading.Lock()
        self._import_inflight_bytes = 0
        # SLO-adaptive ingest derating (ISSUE r19 tentpole 4, config
        # `ingest-derate`): when the attached monitor's derate ladder is
        # raised (read-latency objective burning), admit 1-in-2^level
        # imports and shed the rest with 429 + a Retry-After scaled to
        # the ladder — overload degrades the writer, not the readers.
        self.ingest_derate = True
        self._derate_seq = 0
        # Per-/query write-call cap (reference MaxWritesPerRequest,
        # config max-writes-per-request; cli.py wires it). 0 = no cap so
        # directly-constructed test APIs stay unbounded.
        self.max_writes_per_request = 0
        # `[metric] service` knob: "none" disables the /metrics
        # exposition endpoint (the in-process registry still accrues —
        # it feeds /debug/vars and the SLO plane).
        self.metric_service = "memory"

    # -- import admission (wired by server/http.py around /import) ---------

    def begin_import(self, nbytes: int):
        """Admit one import request of `nbytes` body bytes, or refuse:
        returns None when admitted (caller MUST call end_import(nbytes)
        in a finally block), else (status, code, reason[, retry_after])
        for the shed response. Sheds are counted as
        import_shed_total{reason} / import_derated_total{reason}."""
        from pilosa_tpu.core.fragment import WAL_BACKLOG
        from pilosa_tpu.utils.stats import global_stats

        if self.ingest_derate and self.monitor is not None:
            level = self.monitor.derate_level()
            if level > 0:
                with self._import_lock:
                    self._derate_seq += 1
                    admit = self._derate_seq % (1 << level) == 0
                if not admit:
                    # Deterministic 1-in-2^level counter (not random):
                    # a well-behaved writer retrying on Retry-After sees
                    # steady fractional admission, and the ingest-leg
                    # bench is reproducible. Retry-After scales with the
                    # ladder so backoff deepens as the burn persists.
                    global_stats.with_tags("reason:read-slo").count(
                        "import_derated_total"
                    )
                    return (
                        429,
                        "import-derated",
                        "read-slo",
                        float(1 << (level - 1)),
                    )
        if self.max_pending_wal > 0 and WAL_BACKLOG.ops > self.max_pending_wal:
            # The WAL/snapshot plane is behind: admitting more writes
            # only deepens the un-snapshotted backlog (and the recovery
            # replay a crash would pay). 503: retry after the background
            # snapshots drain, not after an in-flight request finishes.
            global_stats.with_tags("reason:wal-backlog").count(
                "import_shed_total"
            )
            return (503, "wal-backlog", "wal-backlog")
        with self._import_lock:
            over = (
                self.max_import_bytes > 0
                and self._import_inflight_bytes + nbytes > self.max_import_bytes
                # A single request larger than the whole cap must still
                # be admitted when nothing else is in flight, or it
                # could never succeed at any retry pace.
                and self._import_inflight_bytes > 0
            )
            if not over:
                self._import_inflight_bytes += nbytes
                global_stats.gauge(
                    "import_inflight_bytes", self._import_inflight_bytes
                )
                return None
        global_stats.with_tags("reason:inflight-bytes").count(
            "import_shed_total"
        )
        return (429, "import-overloaded", "inflight-bytes")

    def end_import(self, nbytes: int) -> None:
        from pilosa_tpu.utils.stats import global_stats

        with self._import_lock:
            self._import_inflight_bytes = max(
                0, self._import_inflight_bytes - nbytes
            )
            global_stats.gauge(
                "import_inflight_bytes", self._import_inflight_bytes
            )

    # -- admission control (wired by server/http.py around /query) ---------

    def begin_query(self) -> bool:
        """Admit one query execution, or refuse (False) when the in-flight
        cap is reached. Callers that get True MUST call end_query() in a
        finally block. Exported as the http_inflight_queries gauge."""
        from pilosa_tpu.utils.stats import global_stats

        # Gauge writes stay INSIDE the lock: written outside with a
        # captured count, two interleaved begin/end calls could publish
        # their snapshots out of order and leave the gauge wrong until
        # the next query (code review r11). Lock order is always
        # _inflight_lock -> stats lock; nothing takes them reversed.
        with self._inflight_lock:
            if (
                self.max_inflight_queries > 0
                and self._inflight_queries >= self.max_inflight_queries
            ):
                return False
            self._inflight_queries += 1
            global_stats.gauge("http_inflight_queries", self._inflight_queries)
        return True

    def end_query(self) -> None:
        from pilosa_tpu.utils.stats import global_stats

        with self._inflight_lock:
            self._inflight_queries -= 1
            global_stats.gauge("http_inflight_queries", self._inflight_queries)

    def _validate_state(self, method: str) -> None:
        if self.cluster is None or method in _STATE_EXEMPT:
            return
        state = self.cluster.state()
        if state not in ("NORMAL", "DEGRADED"):
            raise APIError(f"cluster is in state {state}", status=503)

    # -- query -------------------------------------------------------------

    def query_results(
        self,
        index: str,
        query: str,
        shards: Optional[list[int]] = None,
        column_attrs: bool = False,
        exclude_row_attrs: bool = False,
        exclude_columns: bool = False,
        remote: bool = False,
        cache_bypass: bool = False,
        wire_sink: Optional[list] = None,
    ) -> tuple[list[Any], list[dict]]:
        """Raw executor results + column attr sets (shared by the JSON and
        protobuf response encoders)."""
        self._validate_state("Query")
        from pilosa_tpu.pql import ParseError

        if self.max_writes_per_request > 0:
            # reference api.go MaxWritesPerRequest: bound the write calls
            # one /query body may carry (Query.write_call_n existed for
            # this; the config-drift rule caught the knob parsed but
            # never enforced). Parse HERE, under the same profile phase
            # the executor would use, and hand the tree down — the
            # executor accepts pre-parsed queries, so a multi-kilobyte
            # write batch (too big for the parse cache) is still parsed
            # exactly once (code review r13).
            from pilosa_tpu.pql.parser import parse_string
            from pilosa_tpu.utils.qprofile import current_profile

            try:
                with current_profile().phase("parse"):
                    parsed = parse_string(query)
            except ParseError as e:
                raise APIError(str(e)) from e
            writes = parsed.write_call_n()
            if writes > self.max_writes_per_request:
                raise APIError(
                    f"query contains {writes} write calls, over the "
                    f"max-writes-per-request cap "
                    f"({self.max_writes_per_request})",
                    status=400, code="too-many-writes",
                )
            query = parsed

        opt = ExecOptions(
            remote=remote,
            exclude_row_attrs=exclude_row_attrs,
            exclude_columns=exclude_columns,
            column_attrs=column_attrs,
            cache_bypass=cache_bypass,
            wire_sink=wire_sink,
        )
        from pilosa_tpu.cluster.client import ClientError
        from pilosa_tpu.cluster.cluster import ShardUnavailableError
        from pilosa_tpu.utils.deadline import DeadlineExceeded

        from pilosa_tpu.exec.cpu import NotFoundError as ExecNotFound

        try:
            results = self.executor.execute(index, query, shards=shards, opt=opt)
        except ExecNotFound as e:
            raise APIError(str(e), code="not-found") from e
        except (ParseError, QueryError, ValueError) as e:
            raise APIError(str(e)) from e
        except ShardUnavailableError as e:
            raise APIError(str(e), status=503, code="shard-unavailable") from e
        except DeadlineExceeded as e:
            # The query's budget ran out mid-execution: structured 504
            # (the HTTP layer adds Retry-After) — the abandoned legs stop
            # themselves via the propagated header.
            raise APIError(str(e), status=504, code="deadline-exceeded") from e
        except ClientError as e:
            code = getattr(e, "code", "")
            if code == "deadline-exceeded":
                raise APIError(str(e), status=504, code=code) from e
            if code == "replicas-unavailable":
                # The loud-failure invariant surfacing: every replica of
                # a written shard was down/circuit-broken.
                raise APIError(str(e), status=503, code=code) from e
            raise APIError(f"remote node error: {e}", status=502,
                           code="peer-error") from e
        attr_sets: list[dict] = []
        if column_attrs and not exclude_columns:
            attr_sets = self._column_attr_sets(index, results)
        return results, attr_sets

    def query(
        self,
        index: str,
        query: str,
        shards: Optional[list[int]] = None,
        column_attrs: bool = False,
        exclude_row_attrs: bool = False,
        exclude_columns: bool = False,
        remote: bool = False,
        cache_bypass: bool = False,
    ) -> dict[str, Any]:
        results, attr_sets = self.query_results(
            index, query, shards=shards, column_attrs=column_attrs,
            exclude_row_attrs=exclude_row_attrs,
            exclude_columns=exclude_columns, remote=remote,
            cache_bypass=cache_bypass,
        )
        from pilosa_tpu.utils.deadline import DeadlineExceeded, check_deadline
        from pilosa_tpu.utils.qprofile import current_profile

        try:
            check_deadline("serialize")
        except DeadlineExceeded as e:
            raise APIError(str(e), status=504, code="deadline-exceeded") from e
        with current_profile().phase("serialize"):
            out: dict[str, Any] = {
                "results": [
                    self._encode_result(r, exclude_columns) for r in results
                ]
            }
            if column_attrs and not exclude_columns:
                out["columnAttrSets"] = attr_sets
            return out

    def query_bytes(
        self,
        index: str,
        query: str,
        shards: Optional[list[int]] = None,
        column_attrs: bool = False,
        exclude_row_attrs: bool = False,
        exclude_columns: bool = False,
        remote: bool = False,
        cache_bypass: bool = False,
    ) -> bytes:
        """The serving path's JSON response body as BYTES (with trailing
        newline), byte-identical to json.dumps(self.query(...)) + "\\n"
        (pinned by tests/test_fastjson.py). Two collapses vs query()
        (ISSUE r14): results encode through utils/fastjson's vectorized
        template fragments instead of tolist()+json.dumps, and a result-
        cache hit splices its entry's pre-encoded wire bytes straight
        into the envelope — hits skip `serialize` work entirely."""
        from pilosa_tpu.utils import fastjson

        wire_sink: list = []
        results, attr_sets = self.query_results(
            index, query, shards=shards, column_attrs=column_attrs,
            exclude_row_attrs=exclude_row_attrs,
            exclude_columns=exclude_columns, remote=remote,
            cache_bypass=cache_bypass, wire_sink=wire_sink,
        )
        from pilosa_tpu.utils.deadline import DeadlineExceeded, check_deadline
        from pilosa_tpu.utils.qprofile import current_profile

        try:
            check_deadline("serialize")
        except DeadlineExceeded as e:
            raise APIError(str(e), status=504, code="deadline-exceeded") from e
        cache = getattr(self.executor, "rescache", None)
        flags = ("json", exclude_columns)
        with current_profile().phase("serialize"):
            frags: list[bytes] = []
            for i, r in enumerate(results):
                token = wire_sink[i] if i < len(wire_sink) else None
                frag = (
                    cache.wire_for(token, flags)
                    if cache is not None else None
                )
                if frag is None:
                    frag = fastjson.encode_result(r, exclude_columns)
                    if cache is not None and token is not None:
                        cache.attach_wire(token, flags, frag)
                frags.append(frag)
            return fastjson.response_body(
                frags,
                attr_sets if (column_attrs and not exclude_columns)
                else None,
            )

    def query_proto(self, index: str, query: str, **kw) -> bytes:
        """Protobuf QueryResponse (reference QueryResponse public.proto:66;
        Go client libraries speak this both ways)."""
        from pilosa_tpu.server.wire import encode_query_response
        from pilosa_tpu.utils.qprofile import current_profile

        results, attr_sets = self.query_results(index, query, **kw)
        with current_profile().phase("serialize"):
            return encode_query_response(results, attr_sets)

    def _encode_result(self, r: Any, exclude_columns: bool) -> Any:
        from pilosa_tpu.core.row import Row
        from pilosa_tpu.exec.result import result_to_json

        if isinstance(r, Row):
            out: dict[str, Any] = {"attrs": r.attrs or {}}
            if r.keys:
                out["keys"] = r.keys
            elif not exclude_columns:
                # lint: allow-hot-serialize(legacy dict path kept as the byte-compat oracle for query_bytes; tests diff the two)
                out["columns"] = r.columns().tolist()
            else:
                out["columns"] = []
            return out
        return result_to_json(r)

    def _column_attr_sets(self, index: str, results: list) -> list[dict]:
        from pilosa_tpu.core.row import Row

        idx = self.holder.index(index)
        if idx is None or idx.column_attr_store is None:
            return []
        seen: set[int] = set()
        for r in results:
            if isinstance(r, Row):
                # lint: allow-hot-serialize(attr plane: the column set keys Python dict lookups into the attr store, not serialization)
                seen.update(int(c) for c in r.columns().tolist())
        out = []
        for col in sorted(seen):
            attrs = idx.column_attr_store.attrs(col)
            if attrs:
                out.append({"id": col, "attrs": attrs})
        return out

    # -- schema ------------------------------------------------------------

    def create_index(self, name: str, options: Optional[dict] = None) -> dict:
        self._validate_state("CreateIndex")
        options = options or {}
        opts = IndexOptions(
            keys=bool(options.get("keys", False)),
            track_existence=bool(options.get("trackExistence", True)),
        )
        try:
            idx = self.holder.create_index(name, opts)
        except ValueError as e:
            if "exists" in str(e):
                raise ConflictError(str(e)) from e
            raise APIError(str(e)) from e
        if self.cluster is not None:
            self.cluster.broadcast_schema()
        return {"name": name, "options": idx.options.to_dict()}

    def delete_index(self, name: str) -> None:
        self._validate_state("DeleteIndex")
        try:
            self.holder.delete_index(name)
        except KeyError as e:
            raise NotFoundError(f"index not found: {name}") from e
        if self.cluster is not None:
            self.cluster.broadcast_schema()

    def create_field(self, index: str, name: str, options: Optional[dict] = None) -> dict:
        self._validate_state("CreateField")
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        fo = self._field_options(options or {})
        try:
            f = idx.create_field(name, fo)
        except ValueError as e:
            if "exists" in str(e):
                raise ConflictError(str(e)) from e
            raise APIError(str(e)) from e
        if self.cluster is not None:
            self.cluster.broadcast_schema()
        return {"name": name, "options": f.options.to_dict()}

    @staticmethod
    def _field_options(o: dict) -> FieldOptions:
        from pilosa_tpu.core import field as field_mod

        typ = o.get("type", "set")
        if typ == "set":
            fo = field_mod.options_for_set(
                o.get("cacheType", "ranked"), o.get("cacheSize", 50000)
            )
        elif typ == "int":
            fo = field_mod.options_for_int(o.get("min", 0), o.get("max", 0))
        elif typ == "time":
            fo = field_mod.options_for_time(
                o.get("timeQuantum", ""), o.get("noStandardView", False)
            )
        elif typ == "mutex":
            fo = field_mod.options_for_mutex(
                o.get("cacheType", "ranked"), o.get("cacheSize", 50000)
            )
        elif typ == "bool":
            fo = field_mod.options_for_bool()
        else:
            raise APIError(f"invalid field type: {typ}")
        fo.keys = bool(o.get("keys", False))
        return fo

    def delete_field(self, index: str, name: str) -> None:
        self._validate_state("DeleteField")
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        try:
            idx.delete_field(name)
        except KeyError as e:
            raise NotFoundError(f"field not found: {name}") from e
        if self.cluster is not None:
            self.cluster.broadcast_schema()

    def schema(self) -> dict:
        return {"indexes": self.holder.schema()}

    def apply_schema(self, schema: dict) -> None:
        """POST /schema: idempotent create of indexes+fields (reference
        api.go ApplySchema)."""
        for idx_def in schema.get("indexes", []):
            idx = self.holder.create_index_if_not_exists(
                idx_def["name"],
                IndexOptions(
                    keys=idx_def.get("options", {}).get("keys", False),
                    track_existence=idx_def.get("options", {}).get("trackExistence", True),
                ),
            )
            for f_def in idx_def.get("fields", []):
                if idx.field(f_def["name"]) is None:
                    idx.create_field(f_def["name"], self._field_options(f_def.get("options", {})))

    # -- imports -----------------------------------------------------------

    def import_bits(
        self,
        index: str,
        field: str,
        row_ids: list[int],
        column_ids: list[int],
        row_keys: Optional[list[str]] = None,
        column_keys: Optional[list[str]] = None,
        timestamps: Optional[list[int]] = None,
        clear: bool = False,
        remote: bool = False,
    ) -> None:
        """reference api.go Import :920 (key translation + shard routing +
        existence). remote=True marks a peer-routed request that must
        apply locally without re-routing."""
        self._validate_state("Import")
        from pilosa_tpu.utils.stats import global_stats

        global_stats.with_tags(f"index:{index}", f"field:{field}").count(
            "import_bits_total", len(column_ids) or len(column_keys or [])
        )
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        f = idx.field(field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        if column_keys:
            if idx.translate_store is None:
                raise APIError("index does not use string keys")
            column_ids = idx.translate_store.translate_keys(column_keys)
        if row_keys:
            if f.translate_store is None:
                raise APIError("field does not use string keys")
            row_ids = f.translate_store.translate_keys(row_keys)
        if self.cluster is not None and not remote:
            from pilosa_tpu.cluster.client import ClientError

            try:
                self._route_import(index, field, row_ids, column_ids,
                                   timestamps, clear)
            except ClientError as e:
                raise self._map_import_client_error(e) from e
            return
        rows = np.asarray(row_ids, dtype=np.uint64)
        cols = np.asarray(column_ids, dtype=np.uint64)
        ts = None
        if timestamps and any(timestamps):
            ts = [parse_time(t) if t else None for t in timestamps]
        try:
            f.import_bits(rows, cols, timestamps=ts, clear=clear)
        except ValueError as e:
            raise APIError(str(e)) from e
        ef = idx.existence_field()
        if ef is not None and not clear and cols.size:
            ef.import_bits(np.zeros(cols.size, dtype=np.uint64), cols)

    def import_values(
        self,
        index: str,
        field: str,
        column_ids: list[int],
        values: list[int],
        column_keys: Optional[list[str]] = None,
        clear: bool = False,
        remote: bool = False,
    ) -> None:
        self._validate_state("ImportValue")
        from pilosa_tpu.utils.stats import global_stats

        global_stats.with_tags(f"index:{index}", f"field:{field}").count(
            "import_values_total", len(column_ids) or len(column_keys or [])
        )
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        f = idx.field(field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        if column_keys:
            if idx.translate_store is None:
                raise APIError("index does not use string keys")
            column_ids = idx.translate_store.translate_keys(column_keys)
        if self.cluster is not None and not remote:
            from pilosa_tpu.cluster.client import ClientError

            try:
                self._route_import_values(index, field, column_ids, values,
                                          clear)
            except ClientError as e:
                raise self._map_import_client_error(e) from e
            return
        cols = np.asarray(column_ids, dtype=np.uint64)
        try:
            f.import_value(cols, np.asarray(values, dtype=np.int64), clear=clear)
        except ValueError as e:
            raise APIError(str(e)) from e
        ef = idx.existence_field()
        if ef is not None and not clear and cols.size:
            ef.import_bits(np.zeros(cols.size, dtype=np.uint64), cols)

    @staticmethod
    def _map_import_client_error(e) -> "APIError":
        """A fanned-out import leg's peer refusal, translated so the
        originating client sees the peer's backpressure contract —
        429/503/504 + Retry-After + code — instead of an opaque 500
        (ISSUE r8: remote legs propagate the budget like read legs do)."""
        code = getattr(e, "code", "")
        status = getattr(e, "status", 0)
        if code in ("import-overloaded", "overloaded") or status == 429:
            return APIError(str(e), status=429, code=code or "overloaded")
        if code in ("wal-backlog", "unavailable") or status == 503:
            return APIError(str(e), status=503, code=code or "unavailable")
        if code == "deadline-exceeded" or status == 504:
            return APIError(str(e), status=504, code="deadline-exceeded")
        return APIError(f"remote import error: {e}", status=502,
                        code="peer-error")

    # -- cluster import routing (reference api.go:920-1127: bits grouped by
    # shard, each group sent to every owning node) ------------------------

    def _owners_by_node(self, index: str, shards: set[int]):
        """node id -> (node, is_local, set of its shards), over replicas.

        DOWN or circuit-broken replicas are skipped exactly like the
        route_write path (anti-entropy delivers the import when they
        return) — previously one dead replica failed the WHOLE import
        with a 502, which made every import during a rolling restart an
        error instead of a degraded write (ISSUE r9). A shard with NO
        live owner still fails loudly: a silently dropped import is
        unrepairable."""
        topo = self.cluster.topology
        local_id = self.cluster.local_node.id
        out: dict[str, tuple] = {}
        for shard in shards:
            reps = topo.shard_nodes(index, shard)
            live = [
                n for n in reps
                if n.id == local_id or not self.cluster._peer_unwritable(n)
            ]
            if reps and not live:
                err = self.cluster._no_live_replica(index, shard)
                raise APIError(
                    str(err), status=503, code="replicas-unavailable"
                )
            for node in live:
                entry = out.setdefault(node.id, (node, node.id == local_id, set()))
                entry[2].add(shard)
        return out.values()

    def _route_import(self, index, field, row_ids, column_ids, timestamps, clear) -> None:
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        shard_of = [c // SHARD_WIDTH for c in column_ids]
        for node, is_local, node_shards in self._owners_by_node(index, set(shard_of)):
            sel = [i for i, s in enumerate(shard_of) if s in node_shards]
            sub_rows = [row_ids[i] for i in sel]
            sub_cols = [column_ids[i] for i in sel]
            sub_ts = [timestamps[i] for i in sel] if timestamps else None
            if is_local:
                self.import_bits(index, field, sub_rows, sub_cols,
                                 timestamps=sub_ts, clear=clear, remote=True)
            else:
                self.cluster.client.import_bits(
                    node, index, field, 0, sub_rows, sub_cols,
                    timestamps=sub_ts, clear=clear,
                )

    def _route_import_values(self, index, field, column_ids, values, clear) -> None:
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        shard_of = [c // SHARD_WIDTH for c in column_ids]
        for node, is_local, node_shards in self._owners_by_node(index, set(shard_of)):
            sel = [i for i, s in enumerate(shard_of) if s in node_shards]
            sub_cols = [column_ids[i] for i in sel]
            sub_vals = [values[i] for i in sel]
            if is_local:
                self.import_values(index, field, sub_cols, sub_vals,
                                   clear=clear, remote=True)
            else:
                self.cluster.client.import_values(
                    node, index, field, 0, sub_cols, sub_vals, clear=clear
                )

    def import_roaring(
        self, index: str, field: str, shard: int, views: dict[str, bytes],
        clear: bool = False, remote: bool = False,
    ) -> None:
        """reference api.go ImportRoaring :368."""
        self._validate_state("ImportRoaring")
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        f = idx.field(field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        if self.cluster is not None and not remote:
            from pilosa_tpu.cluster.client import ClientError

            try:
                for node, is_local, _ in self._owners_by_node(index, {shard}):
                    if is_local:
                        self.import_roaring(index, field, shard, views,
                                            clear=clear, remote=True)
                    else:
                        self.cluster.client.import_roaring(
                            node, index, field, shard, views, clear=clear
                        )
            except ClientError as e:
                raise self._map_import_client_error(e) from e
            return
        for view_name, data in views.items():
            name = view_name or "standard"
            try:
                f.import_roaring(shard, data, view_name=name, clear=clear)
            except ValueError as e:
                raise APIError(str(e)) from e

    # -- resize (reference api.go:1193-1261) -------------------------------

    def _resizer(self):
        if self.cluster is None or self.cluster.resizer is None:
            raise APIError("cluster resize is not enabled", status=400)
        return self.cluster.resizer

    def _forward_to_coordinator(self, path: str, body: dict) -> dict:
        """Non-coordinator resize endpoints forward to the coordinator
        under one client-timeout deadline (deadline-scope rule): the
        serving thread must not pin on a hung coordinator past one
        budget, and the remaining budget rides X-Pilosa-Deadline."""
        coord = self.cluster.coordinator()
        with deadline_scope(Deadline(self.cluster.client.timeout)):
            return self.cluster.client._do(
                "POST", coord, path, json.dumps(body).encode()
            )

    def resize_add_node(self, body: dict) -> dict:
        """POST /cluster/resize/add-node {id?, uri}. Non-coordinators
        forward to the coordinator (reference routes joins there)."""
        from pilosa_tpu.cluster.resize import ResizeError
        from pilosa_tpu.cluster.topology import Node, URI

        rz = self._resizer()
        if not self.cluster.is_coordinator():
            return self._forward_to_coordinator(
                "/cluster/resize/add-node", body
            )
        uri = URI.parse(body.get("uri", ""))
        node_id = body.get("id") or f"node-{uri.host}-{uri.port}"
        try:
            job = rz.add_node(Node(id=node_id, uri=uri))
        except ResizeError as e:
            raise APIError(str(e), status=400) from e
        return {"job": job, "node": node_id}

    def resize_remove_node(self, node_id: str) -> dict:
        from pilosa_tpu.cluster.resize import ResizeError

        rz = self._resizer()
        if not self.cluster.is_coordinator():
            return self._forward_to_coordinator(
                "/cluster/resize/remove-node", {"id": node_id}
            )
        try:
            job = rz.remove_node(node_id)
        except ResizeError as e:
            raise APIError(str(e), status=400) from e
        return {"job": job, "node": node_id}

    def resize_abort(self) -> None:
        self._validate_state("ResizeAbort")
        rz = self._resizer()
        if not self.cluster.is_coordinator():
            self._forward_to_coordinator("/cluster/resize/abort", {})
            return
        rz.abort()

    def set_coordinator(self, node_id: str) -> dict:
        """POST /cluster/coordinator {id} — manual coordinator move /
        failover (reference api.go:1193-1261 SetCoordinator). Applied
        locally and broadcast best-effort; nodes that miss it converge
        via the failure detector's piggybacked view merge. Works when
        the OLD coordinator is dead — that is the point."""
        if self.cluster is None:
            raise APIError("not clustered", status=400)
        from pilosa_tpu.cluster import broadcast as bc

        if self.cluster.topology.node_by_id(node_id) is None:
            raise APIError(f"node not in cluster: {node_id}", status=400)
        msg = bc.Message.make(bc.MSG_SET_COORDINATOR, id=node_id)
        self.cluster.apply_message(msg)
        self.cluster.broadcaster.send_async(msg)
        return {"coordinator": node_id}

    # -- info --------------------------------------------------------------

    def status(self) -> dict:
        nodes = (
            self.cluster.nodes_json()
            if self.cluster is not None
            else [{"id": "local",
                   "uri": {"scheme": self.local_scheme, "host": self.local_host,
                           "port": self.local_port},
                   "isCoordinator": True, "state": "READY"}]
        )
        out = {
            "state": self.cluster.state() if self.cluster is not None else "NORMAL",
            "nodes": nodes,
            "localID": self.cluster.node_id if self.cluster is not None else "local",
        }
        if self.cluster is not None and self.cluster.resizer is not None:
            # A follower frozen mid-resize reports the job it is frozen
            # on; a promoted coordinator's probes read this and abort the
            # orphan for it (ISSUE r9 tentpole 1).
            rz = self.cluster.resizer.follower_status()
            if rz:
                out["resize"] = rz
        if self.cluster is not None:
            # View-epoch piggyback on the probe plane (ISSUE r15
            # tentpole 3): the failure detector polls /status every
            # ~interval second, so every peer's epoch map advances even
            # for indexes no fan-out has touched — this is what bounds
            # the clustered result cache's staleness window for writes
            # that never route through the coordinator. Memoized on the
            # generation watermark (read BEFORE the walk, same protocol
            # as view_epochs_header) so idle probes don't re-walk the
            # schema.
            from pilosa_tpu.core.view import BOOT_ID, generation_watermark

            wm = generation_watermark()
            got_wm, got_indexes = self._epoch_status_memo
            if got_wm != wm or got_indexes is None:
                got_indexes = self.view_epochs_payload()["indexes"]
                if generation_watermark() == wm:
                    # Same torn-walk discipline as view_epochs_header:
                    # a walk a mint landed inside ships once, unmemoized.
                    self._epoch_status_memo = (wm, got_indexes)
            out["indexEpochs"] = got_indexes
            out["indexEpochsBoot"] = BOOT_ID
        return out

    def view_epochs_header(self, index: str) -> str:
        """Encoded X-Pilosa-View-Epochs value for one index, memoized on
        the process-wide generation watermark: the watermark is read
        BEFORE the walk and re-checked AFTER, so a memo hit proves
        nothing minted since the stored payload was assembled (no
        staleness, the piggyback's synchronous write-invalidation
        contract holds). A walk the re-check catches a mint inside may
        be TORN (one view's generation read pre-mint, another's post) —
        it still ships (the very mint that tore it will raise the
        watermark and the next report supersedes), but it must never be
        memoized: a torn payload under a settled watermark would serve
        the stale generation until the next mint anywhere."""
        from pilosa_tpu.core.view import generation_watermark

        wm = generation_watermark()
        memo = self._epoch_header_memo
        for got_index, got_wm, got_enc in memo:
            if got_index == index and got_wm == wm:
                return got_enc
        enc = json.dumps(
            self.view_epochs_payload(index), separators=(",", ":")
        )
        if generation_watermark() != wm:
            return enc  # possibly torn: usable once, never memoized
        # Keep other indexes' entries that are still current (a mint
        # anywhere obsoletes every entry), newest first, bounded.
        self._epoch_header_memo = ((index, wm, enc),) + tuple(
            e for e in memo if e[0] != index and e[1] == wm
        )[:7]
        return enc

    def view_epochs_payload(self, index: Optional[str] = None) -> dict:
        """This node's view-epoch report ({"node", "indexes": {index:
        {field: {"structure": int, "views": {view: generation}}}}}) for
        one index or all — the X-Pilosa-View-Epochs piggyback body and
        the /status indexEpochs field. Generations come from the
        wall-seeded process counter (core/view.py), so values are
        unique across restarts and peers compare them by equality."""
        names = [index] if index is not None else list(self.holder.indexes)
        indexes: dict = {}
        for iname in names:
            idx = self.holder.index(iname)
            if idx is None:
                continue
            fields: dict = {}
            for fname in list(idx.fields):
                f = idx.field(fname)
                if f is None:
                    continue
                fields[fname] = {
                    "structure": f.structure_version,
                    "views": {
                        vname: v.generation
                        for vname, v in sorted(list(f.views.items()))
                    },
                }
            indexes[iname] = fields
        from pilosa_tpu.core.view import BOOT_ID

        return {
            "node": self.cluster.node_id if self.cluster is not None else "local",
            # Incarnation token: lets the fold guard tell "this node
            # restarted" (accept the fresh report even if its max
            # generation is lower — a post-clock-step reboot mints
            # below the previous life) from "this report is older".
            "boot": BOOT_ID,
            "indexes": indexes,
        }

    def info(self) -> dict:
        import os

        return {
            "shardWidth": SHARD_WIDTH,
            "cpuPhysicalCores": os.cpu_count(),
            "cpuLogicalCores": os.cpu_count(),
        }

    def max_shards(self) -> dict:
        out = {}
        for name in self.holder.indexes:
            idx = self.holder.index(name)
            av = idx.available_shards()
            out[name] = int(av.max()) if av.any() else 0
        return {"standard": out}

    def recalculate_caches(self) -> None:
        for idx in self.holder.indexes.values():
            for f in idx.fields.values():
                for v in f.views.values():
                    for frag in v.fragments.values():
                        frag.cache.invalidate()

    def export_csv(self, index: str, field: str, shard: Optional[int] = None) -> str:
        """reference handler.go handleGetExport / ctl/export.go.

        shard=None exports the WHOLE field cluster-wide (VERDICT r3
        missing #6): local fragments stream directly; shards this node
        doesn't hold are fetched from a live owner with the shard pinned
        (the reference's ctl/export.go per-shard loop, server side).
        Keyed indexes/fields export keys, not ids (api.go:591)."""
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        f = idx.field(field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        if shard is not None:
            return self._export_shard_local(idx, f, shard)
        parts = []
        # lint: allow-hot-serialize(export walks the schema-sized shard inventory, off the serving path)
        for s in f.available_shards().to_array().tolist():
            s = int(s)
            v = f.view("standard")
            if v is not None and v.fragment(s) is not None:
                parts.append(self._export_shard_local(idx, f, s))
                continue
            if self.cluster is None:
                # Unclustered: an available shard with no local fragment
                # has no bits in this field's standard view — nothing to
                # export for it.
                continue
            from pilosa_tpu.cluster.client import ClientError
            from pilosa_tpu.cluster.topology import NODE_STATE_DOWN

            owners = [
                n
                for n in self.cluster.topology.shard_nodes(index, s)
                if n.id != self.cluster.node_id
                and n.state != NODE_STATE_DOWN
            ]
            got = None
            last_err = None
            for owner in owners:  # every live replica before giving up
                try:
                    # Per-attempt budget (deadline-scope rule): the
                    # remote leg rides X-Pilosa-Deadline so a replica
                    # that stalls mid-export is abandoned after one
                    # client timeout and the next replica is tried.
                    with deadline_scope(Deadline(self.cluster.client.timeout)):
                        got = self.cluster.client.export_csv_shard(
                            owner, index, field, s
                        )
                    break
                except ClientError as e:
                    last_err = e
            if got is None:
                # NEVER return a silently partial export — an operator
                # treats the CSV as a complete backup (code review r4).
                raise APIError(
                    f"shard {s} unavailable for export "
                    f"({len(owners)} live owner(s); last error: {last_err})",
                    status=503,
                )
            parts.append(got)
        return "".join(parts)

    def _export_shard_local(self, idx, f, shard: int) -> str:
        v = f.view("standard")
        frag = v.fragment(shard) if v is not None else None
        if frag is None:
            return ""
        row_tr = f.translate_store if f.options.keys else None
        col_tr = idx.translate_store if idx.options.keys else None
        row_keys: dict[int, str] = {}
        col_keys: dict[int, str] = {}

        def fmt(tr, cache, id_) -> str:
            k = cache.get(id_)
            if k is None:
                k = tr.translate_id(id_)
                cache[id_] = k if k is not None else str(id_)
                k = cache[id_]
            return k

        lines = []
        if row_tr is None and col_tr is None:
            frag.for_each_bit(lambda r, c: lines.append(f"{r},{c}"))
        else:
            frag.for_each_bit(
                lambda r, c: lines.append(
                    f"{fmt(row_tr, row_keys, r) if row_tr else r},"
                    f"{fmt(col_tr, col_keys, c) if col_tr else c}"
                )
            )
        return "\n".join(lines) + ("\n" if lines else "")
