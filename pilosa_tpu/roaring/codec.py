"""Roaring bitmap (de)serialization — byte-compatible with the reference.

Implements the Pilosa roaring file format (reference roaring/roaring.go
writeToUnoptimized at :1054, docs/architecture.md):

  bytes 0-3   cookie = magic 12348 | version<<16 | flags<<24 (LE)
  bytes 4-7   container count (LE u32)
  then per container (12 bytes): key u64, type u16 (1=array,2=bitmap,3=run),
              cardinality-1 u16
  then per container: file offset u32
  then container data: array = N*u16; bitmap = 1024*u64;
              run = count u16 + count*(start u16, last u16) [inclusive]
  then an op log until EOF (reference roaring/roaring.go:4649-4700):
              type u8, value/len u64, fnv32a checksum u32 at [9:13],
              then batch values (8B each) or opN u32 + roaring payload.

Also reads the official RoaringFormatSpec formats (cookies 12346/12347,
reference roaring/unmarshal_binary.go readOfficialHeader at roaring.go:5315).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import BinaryIO, Optional

import numpy as np

from pilosa_tpu.native import fnv32a
from pilosa_tpu.roaring.bitmap import (
    ARRAY_MAX_SIZE,
    BITMAP_N,
    Bitmap,
    Container,
)

MAGIC_NUMBER = 12348
STORAGE_VERSION = 0

# Official RoaringFormatSpec cookies (reference roaring/roaring.go).
SERIAL_COOKIE_NO_RUN = 12346
SERIAL_COOKIE = 12347

TYPE_CODE_ARRAY = 1
TYPE_CODE_BITMAP = 2
TYPE_CODE_RUN = 3

OP_ADD = 0
OP_REMOVE = 1
OP_ADD_BATCH = 2
OP_REMOVE_BATCH = 3
OP_ADD_ROARING = 4
OP_REMOVE_ROARING = 5

_MIN_OP_SIZE = 13


class CorruptWalError(ValueError):
    """Op-log corruption BEFORE the tail: a record that fails its
    checksum (or is structurally impossible) while more valid bytes
    follow it. Unlike a torn tail — which a crash mid-append produces
    legitimately and recovery truncates away — mid-log corruption means
    records AFTER the damage would be lost by truncation, so the caller
    must refuse to open the fragment rather than silently drop data
    (ISSUE r8 tentpole 1).

    `offset` is the file offset of the bad record; `reason` is a short
    machine-stable token (checksum | op-type | bounds)."""

    def __init__(self, msg: str, offset: int, reason: str):
        super().__init__(msg)
        self.offset = offset
        self.reason = reason


@dataclass
class ReplayInfo:
    """What a WAL replay actually did — the recovery contract's receipt.

    ops_applied:   op records applied (each batch record is ONE op here;
                   Bitmap.op_n still advances by changed-value counts).
    torn_offset:   file offset of a detected torn FINAL record (the
                   SIGKILL-mid-append shape: truncated, or checksum-
                   failing with nothing after it), or None when the log
                   replayed clean to EOF. The caller truncates the file
                   back to this offset to restore the consistent prefix.
    torn_reason:   short token for the torn detection (truncated |
                   checksum | short-record), "" when torn_offset is None.
    """

    ops_applied: int = 0
    torn_offset: Optional[int] = None
    torn_reason: str = ""


def _encoded_container(c: Container) -> tuple[int, bytes]:
    """Pick the smallest of array/bitmap/run encodings (reference Optimize)."""
    n = c.n
    runs = c.runs()
    run_size = 2 + 4 * runs.shape[0]
    array_size = 2 * n
    bitmap_size = 8 * BITMAP_N
    best = min(run_size, array_size, bitmap_size)
    if best == run_size and run_size < array_size and run_size < bitmap_size:
        # runs serialized as [start, last] inclusive (docs/architecture.md)
        body = struct.pack("<H", runs.shape[0]) + runs.astype("<u2").tobytes()
        return TYPE_CODE_RUN, body
    if n <= ARRAY_MAX_SIZE and array_size <= bitmap_size:
        return TYPE_CODE_ARRAY, c.positions().astype("<u2").tobytes()
    return TYPE_CODE_BITMAP, c.bitmap_words().astype("<u8").tobytes()


def serialize(b: Bitmap) -> bytes:
    """Serialize without the op log (callers append ops separately)."""
    entries = []
    for key in b.keys():
        c = b.container(key)
        if c is None or c.n == 0:
            continue
        typ, body = _encoded_container(c)
        entries.append((key, typ, c.n, body))

    header_size = 8
    out = bytearray()
    cookie = MAGIC_NUMBER | (STORAGE_VERSION << 16) | ((b.flags & 0xFF) << 24)
    out += struct.pack("<II", cookie, len(entries))
    for key, typ, n, _ in entries:
        out += struct.pack("<QHH", key, typ, n - 1)
    offset = header_size + len(entries) * 12 + len(entries) * 4
    for _, _, _, body in entries:
        out += struct.pack("<I", offset & 0xFFFFFFFF)
        offset += len(body)
    for _, _, _, body in entries:
        out += body
    return bytes(out)


def serialized_size(b: Bitmap) -> int:
    return len(serialize(b))


def deserialize(data: bytes, b: Optional[Bitmap] = None,
                info: Optional[ReplayInfo] = None) -> Bitmap:
    """Parse either Pilosa or official roaring format, applying any op log.

    `info` (fragment recovery only) opts the op-log replay into the
    torn-tail contract documented on apply_ops and receives the replay
    receipt; without it any damage raises, as wire payloads require."""
    if b is None:
        b = Bitmap()
    if len(data) == 0:
        return b
    if len(data) < 8:
        raise ValueError(f"data too small: {len(data)} bytes")
    file_magic = struct.unpack_from("<H", data, 0)[0]
    try:
        if file_magic == MAGIC_NUMBER:
            return _deserialize_pilosa(data, b, info)
        return _deserialize_official(data, b)
    except struct.error as e:
        # Truncated inputs surface as the module's documented error type.
        raise ValueError(f"malformed roaring data: {e}") from e


def _deserialize_pilosa(data: bytes, b: Bitmap,
                        info: Optional[ReplayInfo] = None) -> Bitmap:
    if len(data) < 8:
        raise ValueError("data too small")
    version = data[2]
    if version != STORAGE_VERSION:
        raise ValueError(f"wrong roaring version: file is v{version}")
    b.flags = data[3]
    key_n = struct.unpack_from("<I", data, 4)[0]
    # Header must hold key_n * (12B descriptive + 4B offset) entries
    # (reference unmarshal_binary.go:150 checks 12B; offsets checked below).
    if len(data) < 8 + key_n * 16:
        raise ValueError(
            f"insufficient data for header + offsets: {key_n} containers, {len(data)} bytes"
        )

    if key_n:
        hdr12 = np.frombuffer(data, dtype=np.uint8, count=key_n * 12, offset=8).reshape(key_n, 12)
        keys = hdr12[:, 0:8].copy().view("<u8").reshape(key_n)
        typs = hdr12[:, 8:10].copy().view("<u2").reshape(key_n)
        cards = hdr12[:, 10:12].copy().view("<u2").reshape(key_n).astype(np.int64) + 1
    else:
        keys = np.empty(0, dtype=np.uint64)
        typs = np.empty(0, dtype=np.uint16)
        cards = np.empty(0, dtype=np.int64)

    ops_offset = 8 + key_n * 12
    # 32-bit offsets with wraparound for >4GB files (reference
    # unmarshal_binary.go:168-176 cycleOffset logic).
    cycle = ops_offset & ~((1 << 32) - 1)
    prev32 = ops_offset & 0xFFFFFFFF
    off_base = 8 + key_n * 12
    for i in range(key_n):
        off32 = struct.unpack_from("<I", data, off_base + i * 4)[0]
        if off32 < prev32:
            cycle += 1 << 32
        prev32 = off32
        offset = off32 + cycle
        if offset >= len(data) and cards[i] > 0:
            raise ValueError(f"offset out of bounds: off={offset}, len={len(data)}")
        typ = int(typs[i])
        n = int(cards[i])
        if typ == TYPE_CODE_ARRAY:
            arr = np.frombuffer(data, dtype="<u2", count=n, offset=offset).copy()
            b.put_container(int(keys[i]), Container.from_positions(arr))
            ops_offset = offset + n * 2
        elif typ == TYPE_CODE_BITMAP:
            words = np.frombuffer(data, dtype="<u8", count=BITMAP_N, offset=offset).copy()
            b.put_container(int(keys[i]), Container.from_bitmap_words(words, n))
            ops_offset = offset + BITMAP_N * 8
        elif typ == TYPE_CODE_RUN:
            run_n = struct.unpack_from("<H", data, offset)[0]
            runs = (
                np.frombuffer(data, dtype="<u2", count=run_n * 2, offset=offset + 2)
                .copy()
                .reshape(run_n, 2)
                .astype(np.int64)
            )
            b.put_container(int(keys[i]), Container.from_runs(runs))
            ops_offset = offset + 2 + run_n * 4
        else:
            raise ValueError(f"unsupported container type {typ}")

    apply_ops(b, data, ops_offset, info)
    return b


def _deserialize_official(data: bytes, b: Bitmap) -> Bitmap:
    """Official RoaringFormatSpec (16-bit keys, low 2^32 bit space only)."""
    if len(data) < 8:
        raise ValueError("buffer too small")
    cookie = struct.unpack_from("<I", data, 0)[0]
    pos = 4
    is_run = None
    if cookie == SERIAL_COOKIE_NO_RUN:
        key_n = struct.unpack_from("<I", data, pos)[0]
        pos += 4
        have_runs = False
    elif cookie & 0xFFFF == SERIAL_COOKIE:
        have_runs = True
        key_n = (cookie >> 16) + 1
        run_bitmap_size = (key_n + 7) // 8
        is_run = data[pos : pos + run_bitmap_size]
        pos += run_bitmap_size
    else:
        raise ValueError("did not find expected serialCookie in header")
    if key_n > (1 << 16):
        raise ValueError("more than 2^16 containers is impossible")

    hdr_pos = pos
    pos += 4 * key_n  # past descriptive header

    entries = []
    for i in range(key_n):
        key = struct.unpack_from("<H", data, hdr_pos + i * 4)[0]
        card = struct.unpack_from("<H", data, hdr_pos + i * 4 + 2)[0] + 1
        if have_runs and is_run is not None and (is_run[i // 8] >> (i % 8)) & 1:
            typ = TYPE_CODE_RUN
        elif card <= ARRAY_MAX_SIZE:
            typ = TYPE_CODE_ARRAY
        else:
            typ = TYPE_CODE_BITMAP
        entries.append((key, typ, card))

    # The official format has an offset section when there are no runs
    # (always written by the reference when !haveRuns); with runs the
    # containers follow immediately and run lengths are [start, length].
    if not have_runs:
        offsets = [struct.unpack_from("<I", data, pos + i * 4)[0] for i in range(key_n)]
        for (key, typ, card), offset in zip(entries, offsets):
            if typ == TYPE_CODE_ARRAY:
                arr = np.frombuffer(data, dtype="<u2", count=card, offset=offset).copy()
                b.put_container(key, Container.from_positions(arr))
            else:
                words = np.frombuffer(data, dtype="<u8", count=BITMAP_N, offset=offset).copy()
                b.put_container(key, Container.from_bitmap_words(words, card))
    else:
        for key, typ, card in entries:
            if typ == TYPE_CODE_RUN:
                run_n = struct.unpack_from("<H", data, pos)[0]
                pos += 2
                runs = (
                    np.frombuffer(data, dtype="<u2", count=run_n * 2, offset=pos)
                    .copy()
                    .reshape(run_n, 2)
                    .astype(np.int64)
                )
                runs[:, 1] = runs[:, 0] + runs[:, 1]  # start,length -> start,last
                b.put_container(key, Container.from_runs(runs))
                pos += run_n * 4
            elif typ == TYPE_CODE_ARRAY:
                arr = np.frombuffer(data, dtype="<u2", count=card, offset=pos).copy()
                b.put_container(key, Container.from_positions(arr))
                pos += card * 2
            else:
                words = np.frombuffer(data, dtype="<u8", count=BITMAP_N, offset=pos).copy()
                b.put_container(key, Container.from_bitmap_words(words, card))
                pos += BITMAP_N * 8
    return b


# ---------------------------------------------------------------------------
# Op log
# ---------------------------------------------------------------------------


def encode_op(typ: int, value: int = 0, values: Optional[np.ndarray] = None,
              roaring: bytes = b"", op_n: int = 0) -> bytes:
    """Encode one op record (reference roaring/roaring.go op.WriteTo)."""
    if typ in (OP_ADD, OP_REMOVE):
        buf = bytearray(13)
        buf[0] = typ
        struct.pack_into("<Q", buf, 1, value)
        payload = b""
    elif typ in (OP_ADD_BATCH, OP_REMOVE_BATCH):
        vals = np.asarray(values, dtype="<u8")
        buf = bytearray(13 + vals.size * 8)
        buf[0] = typ
        struct.pack_into("<Q", buf, 1, vals.size)
        buf[13:] = vals.tobytes()
        payload = b""
    elif typ in (OP_ADD_ROARING, OP_REMOVE_ROARING):
        buf = bytearray(17)
        buf[0] = typ
        struct.pack_into("<Q", buf, 1, len(roaring))
        struct.pack_into("<I", buf, 13, op_n)
        payload = roaring
    else:
        raise ValueError(f"unknown op type {typ}")
    h = fnv32a(bytes(buf[0:9]))
    h = fnv32a(bytes(buf[13:]), h)
    if payload:
        h = fnv32a(payload, h)
    struct.pack_into("<I", buf, 9, h)
    return bytes(buf) + payload


def _op_size(typ: int, value: int) -> int:
    if typ in (OP_ADD, OP_REMOVE):
        return 13
    if typ in (OP_ADD_BATCH, OP_REMOVE_BATCH):
        return 13 + 8 * value
    return 17 + value  # roaring ops: value is payload length


def apply_ops(b: Bitmap, data: bytes, offset: int,
              info: Optional[ReplayInfo] = None) -> int:
    """Replay the op log from offset to EOF. Returns number of ops applied.

    reference roaring/unmarshal_binary.go:207-228 (checksum-verified replay,
    op.apply at roaring/roaring.go:4669).

    Torn-tail contract (ISSUE r8): with `info` supplied (the fragment
    recovery path), a damaged FINAL record — truncated mid-append, or
    checksum-failing with nothing after it, the shapes a SIGKILL during
    the WAL append produces — stops the replay at the last good record
    and reports the torn offset in `info` instead of raising; the caller
    truncates the file there. Damage with MORE bytes after it (a
    checksum-failing or structurally impossible record before the tail)
    is mid-log corruption: truncating there would drop the records
    behind it, so it always raises CorruptWalError and the fragment
    refuses to open. Without `info` (wire payloads, block merges) every
    damage class raises, exactly as before — a peer's serialized bitmap
    has no legitimate torn tail.
    """
    n_ops = 0
    pos = offset
    while pos < len(data):
        if len(data) - pos < _MIN_OP_SIZE:
            if info is not None:
                info.torn_offset, info.torn_reason = pos, "short-record"
                break
            raise ValueError(f"op data out of bounds: len={len(data) - pos}")
        typ = data[pos]
        if typ > OP_REMOVE_ROARING:
            # Never a torn shape: a partial append is a PREFIX of a valid
            # record, whose first byte is a valid type — an impossible
            # type is a flipped bit, and record boundaries past it are
            # unknowable, so even at the tail this refuses.
            raise CorruptWalError(
                f"unknown op type {typ} at offset {pos}", pos, "op-type"
            )
        value = struct.unpack_from("<Q", data, pos + 1)[0]
        size = _op_size(typ, value)
        if pos + size > len(data):
            if info is not None:
                info.torn_offset, info.torn_reason = pos, "truncated"
                break
            raise ValueError("op data truncated")
        want = struct.unpack_from("<I", data, pos + 9)[0]
        h = fnv32a(data[pos : pos + 9])
        h = fnv32a(data[pos + 13 : pos + size], h)
        if h != want:
            if info is not None and pos + size == len(data):
                # Checksum-failing FINAL record: the mid-append crash
                # shape (payload bytes landed, some garbage/stale).
                info.torn_offset, info.torn_reason = pos, "checksum"
                break
            raise CorruptWalError(
                f"op checksum mismatch at offset {pos}", pos, "checksum"
            )
        if typ == OP_ADD:
            b.add(value, log=False)
            b.op_n += 1
        elif typ == OP_REMOVE:
            b.remove(value, log=False)
            b.op_n += 1
        elif typ in (OP_ADD_BATCH, OP_REMOVE_BATCH):
            vals = np.frombuffer(data, dtype="<u8", count=value, offset=pos + 13).copy()
            if typ == OP_ADD_BATCH:
                b.add_many(vals, log=False)
            else:
                b.remove_many(vals, log=False)
            b.op_n += int(value)
        else:
            payload = data[pos + 17 : pos + 17 + value]
            # opN stored in the record is the write-time changed count
            # (reference op.count() for roaring ops).
            op_n = struct.unpack_from("<I", data, pos + 13)[0]
            b.import_roaring_bits(bytes(payload), clear=(typ == OP_REMOVE_ROARING), log=False)
            b.op_n += op_n
        pos += size
        n_ops += 1
    if info is not None:
        info.ops_applied += n_ops
    return n_ops


class OpWriter:
    """Appends checksummed op records to a file (the fragment WAL).

    Attached to a Bitmap as bitmap.op_writer (reference fragment.go:455).
    Callers should hand in an unbuffered file (fragment.open uses
    buffering=0) so each record hits the OS immediately and a process crash
    loses nothing — matching the reference's unbuffered Go file writes;
    fsync is left to the OS like the reference does. flush() covers
    buffered writers.
    """

    def __init__(self, f: BinaryIO):
        self.f = f

    def _write(self, record: bytes) -> None:
        self.f.write(record)

    def append_add(self, v: int) -> None:
        self._write(encode_op(OP_ADD, value=v))

    def append_remove(self, v: int) -> None:
        self._write(encode_op(OP_REMOVE, value=v))

    def append_add_batch(self, vs: np.ndarray) -> None:
        self._write(encode_op(OP_ADD_BATCH, values=vs))

    def append_remove_batch(self, vs: np.ndarray) -> None:
        self._write(encode_op(OP_REMOVE_BATCH, values=vs))

    def append_roaring(self, data: bytes, op_n: int, clear: bool) -> None:
        typ = OP_REMOVE_ROARING if clear else OP_ADD_ROARING
        self._write(encode_op(typ, roaring=data, op_n=op_n))

    def flush(self) -> None:
        self.f.flush()
