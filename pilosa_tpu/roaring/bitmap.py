"""Roaring bitmap core: containers and the 64-bit Bitmap.

Mirrors the semantics of reference roaring/roaring.go (Bitmap, Container,
set-algebra ops Intersect/Union/Difference/Xor/Shift/Flip at
roaring/roaring.go:595,620,891,918,946,1683; IntersectionCount :570;
Count/CountRange :407,438; OffsetRange :537) with numpy-vectorized container
kernels instead of per-container-type Go loops.
"""

from __future__ import annotations

import bisect
import os
from typing import Iterable, Iterator, Optional

import numpy as np

# Invariant-checking mode (reference roaringparanoia build tag): every
# container entering a Bitmap is validated. Off by default — it's a
# correctness harness for tests/debugging, not a production cost.
PARANOIA = os.environ.get("PILOSA_TPU_PARANOIA", "").lower() in ("1", "true")

# A container covers 2^16 bit positions (reference roaring/roaring.go:64-69).
CONTAINER_WIDTH = 1 << 16
# Max cardinality stored as a sorted uint16 array (reference ArrayMaxSize).
ARRAY_MAX_SIZE = 4096
# uint64 words in a bitmap container (reference bitmapN).
BITMAP_N = CONTAINER_WIDTH // 64
# Largest container key: 2^64 bit space / 2^16 container width.
MAX_CONTAINER_KEY = (1 << 48) - 1

TYPE_ARRAY = "array"
TYPE_BITMAP = "bitmap"
# First-class in-memory RLE containers (VERDICT r3 missing #5; reference
# roaring.go:64-69,1940-1943): data is uint16[R, 2] of [start, last]
# INCLUSIVE runs, sorted, non-overlapping, non-adjacent. Reads (contains,
# counts, pack, serialize) AND set algebra against run/array peers are
# run-native (VERDICT r4 #4; reference run-aware op matrix around
# roaring.go:2599-2790) — a runny container survives queries without
# ever materializing its 8 KiB bitmap twin. Ops against bitmap peers
# materialize (the reference does run×bitmap through the bitmap form
# too); point mutators convert, and optimize() re-packs.
TYPE_RUN = "run"

#: RUN -> array/bitmap twin materializations (run_materializations in
#: tests): time-quantum view queries over runny containers must keep
#: this flat on run/array op pairs.
UNRUN_MATERIALIZATIONS = [0]

_EMPTY_U16 = np.empty(0, dtype=np.uint16)

# Keep a container as runs when its RLE form is smaller than both other
# encodings (the serializer's pick-smallest rule, reference Optimize).
def _runs_win(run_count: int, n: int) -> bool:
    return 4 * run_count < min(2 * n, 8 * BITMAP_N)


def _sorted_member_mask(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Boolean mask over a: a[i] ∈ b (both sorted unique — the array-
    container invariant). A 64 KiB bool lookup over the uint16 domain:
    measured 18 µs vs 64 µs for vectorized binary search and 98 µs for
    np.intersect1d (which re-SORTS the concatenation — that sort alone
    profiled as 75% of the CPU oracle's whole query time)."""
    if a.size == 0 or b.size == 0:
        return np.zeros(a.size, dtype=bool)
    table = np.zeros(CONTAINER_WIDTH, dtype=bool)
    table[b] = True
    return table[a]


def _sorted_union(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Union of two uint16 arrays (sorted-unique NOT required — the
    stable sort + adjacent dedup handle anything; sorted inputs just
    make the radix pass cheap). kind='stable' is radix sort for small
    ints — O(n), no comparison re-sort of sorted runs."""
    out = np.sort(np.concatenate([a, b]), kind="stable")
    if out.size:
        out = out[np.concatenate(([True], out[1:] != out[:-1]))]
    return out


def _positions_to_runs(pos: np.ndarray) -> np.ndarray:
    """Sorted-unique positions -> [[start, last], ...] int64."""
    p = pos.astype(np.int64)
    if p.size == 0:
        return np.empty((0, 2), dtype=np.int64)
    breaks = np.nonzero(np.diff(p) != 1)[0]
    starts = np.concatenate(([0], breaks + 1))
    ends = np.concatenate((breaks, [p.size - 1]))
    return np.stack([p[starts], p[ends]], axis=1)


def _runs_member_mask(runs: np.ndarray, pos: np.ndarray) -> np.ndarray:
    """Boolean mask over pos: pos[i] inside some run. Vectorized: the
    predecessor run by start, then an upper-bound check on its last."""
    if runs.shape[0] == 0 or pos.size == 0:
        return np.zeros(pos.size, dtype=bool)
    starts = runs[:, 0].astype(np.int64)
    lasts = runs[:, 1].astype(np.int64)
    p = pos.astype(np.int64)
    idx = np.searchsorted(starts, p, side="right") - 1
    ok = idx >= 0
    return ok & (p <= lasts[np.clip(idx, 0, starts.size - 1)])


def _intersect_runs(ra: np.ndarray, rb: np.ndarray) -> np.ndarray:
    """Overlap sweep of two sorted run lists -> runs int64 (reference
    intersectRunRun, roaring.go's run-aware op matrix)."""
    out = []
    i = j = 0
    na, nb = ra.shape[0], rb.shape[0]
    while i < na and j < nb:
        s = max(ra[i, 0], rb[j, 0])
        l = min(ra[i, 1], rb[j, 1])
        if s <= l:
            out.append((s, l))
        if ra[i, 1] < rb[j, 1]:
            i += 1
        else:
            j += 1
    return np.array(out, dtype=np.int64).reshape(-1, 2)


def _union_runs(ra: np.ndarray, rb: np.ndarray) -> np.ndarray:
    """Merge + coalesce (adjacent runs fuse) -> runs int64. Vectorized
    interval merge: sort by start, running max of ends, break where the
    next start clears the running end by more than adjacency."""
    allr = np.concatenate([ra, rb]).astype(np.int64)
    if allr.shape[0] == 0:
        return allr.reshape(-1, 2)
    allr = allr[np.argsort(allr[:, 0], kind="stable")]
    starts = allr[:, 0]
    ends = np.maximum.accumulate(allr[:, 1])
    brk = np.nonzero(starts[1:] > ends[:-1] + 1)[0]
    s_idx = np.concatenate(([0], brk + 1))
    e_idx = np.concatenate((brk, [allr.shape[0] - 1]))
    return np.stack([starts[s_idx], ends[e_idx]], axis=1)


def _runs_could_win(n_runs_upper: int, n_upper: int) -> bool:
    """Cheap pre-gate for run-native batch ops: when even the BEST-case
    result (no coalescing losses counted) cannot encode smaller as runs,
    the materialized numpy kernels are faster than the run sweeps — a
    scattered 14k-value with_many through the run path measured ~90x
    slower than the bitmap kernel it replaced (code review r5), and the
    result demoted to a bitmap anyway."""
    return _runs_win(n_runs_upper, max(n_upper, 1))


def _difference_runs(ra: np.ndarray, rb: np.ndarray) -> np.ndarray:
    """ra \\ rb sweep -> runs int64."""
    out = []
    j = 0
    nb = rb.shape[0]
    for s, l in ra.astype(np.int64):
        cur = int(s)
        while j < nb and int(rb[j, 1]) < cur:
            j += 1
        k = j
        while k < nb and int(rb[k, 0]) <= l:
            bs, bl = int(rb[k, 0]), int(rb[k, 1])
            if bs > cur:
                out.append((cur, bs - 1))
            cur = max(cur, bl + 1)
            if cur > l:
                break
            k += 1
        if cur <= l:
            out.append((cur, int(l)))
    return np.array(out, dtype=np.int64).reshape(-1, 2)


def _runs_to_bitmap_words(runs: np.ndarray) -> np.ndarray:
    """Runs [[start, last], ...] -> uint64[1024] coverage words, via a
    boundary-delta cumsum (O(width), no per-position scatter). Deltas
    ACCUMULATE (add.at, coverage = running sum > 0) rather than assign:
    canonical containers are coalesced-disjoint, but a foreign writer
    can serialize adjacent runs like [[0,4],[5,9]] (codec.py builds
    TYPE_RUN straight from wire bytes, validate() is PARANOIA-gated) —
    assignment would let run2's +1 be overwritten by run1's -1 at the
    shared boundary and corrupt the whole mask (code review r7)."""
    d = np.zeros(CONTAINER_WIDTH + 1, dtype=np.int32)
    if runs.shape[0]:
        r = runs.astype(np.int64)
        np.add.at(d, r[:, 0], 1)
        np.add.at(d, r[:, 1] + 1, -1)
    bits = np.cumsum(d[:-1], dtype=np.int32) > 0
    return np.packbits(bits, bitorder="little").view(np.uint64)


def _as_bitmap_words(arr: np.ndarray) -> np.ndarray:
    """Sorted uint16 positions -> uint64[1024] bitmap words."""
    words = np.zeros(BITMAP_N, dtype=np.uint64)
    if arr.size:
        np.bitwise_or.at(words, arr >> 6, np.uint64(1) << (arr.astype(np.uint64) & np.uint64(63)))
    return words


def _bitmap_to_positions(words: np.ndarray) -> np.ndarray:
    """uint64[1024] bitmap words -> sorted uint16 positions."""
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint16)


class Container:
    """One 2^16-bit container: sorted uint16 array or uint64[1024] bitmap.

    Value semantics: operations return new containers; data arrays are treated
    as immutable once attached (the Bitmap mutators replace containers rather
    than editing them in place, which keeps snapshots/row views safe to share
    the way the reference's copy-on-write container freezing does,
    reference roaring/roaring.go Freeze).
    """

    __slots__ = ("typ", "data", "_n")

    def __init__(self, typ: str, data: np.ndarray, n: Optional[int] = None):
        self.typ = typ
        if PARANOIA and isinstance(data, np.ndarray):
            # Sentinel mode (reference roaringsentinel build tag,
            # roaring_sentinel.go): containers are immutable-by-convention
            # and structurally shared by clones/snapshots; freezing the
            # array makes any accidental in-place mutation raise instead
            # of silently corrupting every sharer.
            data = data.view()
            data.flags.writeable = False
        self.data = data
        if n is None:
            if typ == TYPE_ARRAY:
                n = int(data.size)
            elif typ == TYPE_RUN:
                n = int(
                    (data[:, 1].astype(np.int64) - data[:, 0].astype(np.int64) + 1).sum()
                )
            else:
                n = int(np.bitwise_count(data).sum())
        self._n = n

    # -- constructors ----------------------------------------------------

    def validate(self, key: int = -1) -> None:
        """Invariant checks for paranoia mode (reference roaringparanoia
        build tag, roaring/roaring_paranoia.go:20): array containers must
        be sorted unique within bounds; cached cardinality must match."""
        if self.typ == TYPE_ARRAY:
            a = self.data
            if a.dtype != np.uint16:
                raise AssertionError(f"container {key}: array dtype {a.dtype}")
            if a.size > 1 and not (a[1:] > a[:-1]).all():
                raise AssertionError(f"container {key}: array not sorted/unique")
            if self._n != int(a.size):
                raise AssertionError(
                    f"container {key}: n={self._n} != array size {a.size}"
                )
        elif self.typ == TYPE_RUN:
            r = self.data
            if r.ndim != 2 or r.shape[1] != 2 or r.dtype != np.uint16:
                raise AssertionError(f"container {key}: run shape {r.shape} {r.dtype}")
            if (r[:, 1] < r[:, 0]).any():
                raise AssertionError(f"container {key}: inverted run")
            if r.shape[0] > 1 and not (
                r[1:, 0].astype(np.int64) > r[:-1, 1].astype(np.int64) + 1
            ).all():
                raise AssertionError(
                    f"container {key}: runs overlap or are adjacent"
                )
            real = int(
                (r[:, 1].astype(np.int64) - r[:, 0].astype(np.int64) + 1).sum()
            )
            if self._n != real:
                raise AssertionError(f"container {key}: n={self._n} != runs {real}")
        else:
            if self.data.size != BITMAP_N:
                raise AssertionError(
                    f"container {key}: bitmap has {self.data.size} words"
                )
            real = int(np.bitwise_count(self.data).sum())
            if self._n != real:
                raise AssertionError(f"container {key}: n={self._n} != popcount {real}")

    @staticmethod
    def empty() -> "Container":
        return Container(TYPE_ARRAY, _EMPTY_U16, 0)

    @staticmethod
    def from_positions(arr: np.ndarray) -> "Container":
        """arr: sorted unique uint16 positions."""
        arr = np.asarray(arr, dtype=np.uint16)
        if arr.size > ARRAY_MAX_SIZE:
            return Container(TYPE_BITMAP, _as_bitmap_words(arr), int(arr.size))
        return Container(TYPE_ARRAY, arr, int(arr.size))

    @staticmethod
    def from_bitmap_words(words: np.ndarray, n: Optional[int] = None) -> "Container":
        if n is None:
            n = int(np.bitwise_count(words).sum())
        if n <= ARRAY_MAX_SIZE:
            return Container(TYPE_ARRAY, _bitmap_to_positions(words), n)
        return Container(TYPE_BITMAP, words, n)

    @staticmethod
    def from_runs(runs: np.ndarray) -> "Container":
        """runs: int array [[start, last], ...] inclusive (codec form).
        Stays RLE in memory when runs are the smallest encoding
        (VERDICT r3 #5 — this used to always inflate to array/bitmap,
        costing 8 KiB of host RAM for a 4-byte full-container run)."""
        n = int((runs[:, 1].astype(np.int64) - runs[:, 0].astype(np.int64) + 1).sum())
        if _runs_win(runs.shape[0], n):
            return Container(TYPE_RUN, np.asarray(runs, dtype=np.uint16), n)
        if n <= ARRAY_MAX_SIZE:
            parts = [np.arange(s, l + 1, dtype=np.uint16) for s, l in runs]
            return Container(TYPE_ARRAY, np.concatenate(parts) if parts else _EMPTY_U16, n)
        bits = np.zeros(CONTAINER_WIDTH, dtype=bool)
        for s, l in runs:
            bits[s : l + 1] = True
        words = np.packbits(bits, bitorder="little").view(np.uint64).copy()
        return Container(TYPE_BITMAP, words, n)

    # -- accessors -------------------------------------------------------

    @property
    def n(self) -> int:
        return self._n

    def positions(self) -> np.ndarray:
        """Sorted uint16 positions regardless of representation."""
        if self.typ == TYPE_ARRAY:
            return self.data
        if self.typ == TYPE_RUN:
            if self.data.shape[0] == 0:
                return _EMPTY_U16
            parts = [
                np.arange(int(s), int(l) + 1, dtype=np.uint16)
                for s, l in self.data
            ]
            return np.concatenate(parts)
        return _bitmap_to_positions(self.data)

    def bitmap_words(self) -> np.ndarray:
        """uint64[1024] words regardless of representation."""
        if self.typ == TYPE_BITMAP:
            return self.data
        if self.typ == TYPE_RUN:
            bits = np.zeros(CONTAINER_WIDTH, dtype=bool)
            for s, l in self.data:
                bits[int(s) : int(l) + 1] = True
            return np.packbits(bits, bitorder="little").view(np.uint64).copy()
        return _as_bitmap_words(self.data)

    def runs(self) -> np.ndarray:
        """Runs [[start, last], ...] inclusive, as int32 (native for RUN
        containers, detected for the others)."""
        if self.typ == TYPE_RUN:
            return self.data.astype(np.int32)
        return _positions_to_runs(self.positions()).astype(np.int32)

    def _unrun(self) -> "Container":
        """RUN -> array/bitmap twin (same bits) for ops with no RLE
        form; identity for the other types. Counted: run/array op pairs
        must never come through here (the run-native paths exist so
        time-quantum views don't allocate twins, VERDICT r4 #4)."""
        if self.typ != TYPE_RUN:
            return self
        UNRUN_MATERIALIZATIONS[0] += 1
        if self._n <= ARRAY_MAX_SIZE:
            return Container(TYPE_ARRAY, self.positions(), self._n)
        return Container(TYPE_BITMAP, self.bitmap_words(), self._n)

    def _i64_runs(self) -> np.ndarray:
        return self.data.astype(np.int64)

    def contains(self, v: int) -> bool:
        if self.typ == TYPE_ARRAY:
            i = np.searchsorted(self.data, np.uint16(v))
            return i < self.data.size and self.data[i] == v
        if self.typ == TYPE_RUN:
            # Find the last run with start <= v; v is inside iff v <= last.
            i = int(np.searchsorted(self.data[:, 0], np.uint16(v), side="right")) - 1
            return i >= 0 and v <= int(self.data[i, 1])
        return bool((int(self.data[v >> 6]) >> (v & 63)) & 1)

    def count_range(self, start: int, end: int) -> int:
        """Count positions in [start, end) within this container."""
        if self.typ == TYPE_ARRAY:
            lo = np.searchsorted(self.data, np.uint16(start), side="left")
            hi = self.data.size if end >= CONTAINER_WIDTH else np.searchsorted(
                self.data, np.uint16(end), side="left"
            )
            return int(hi - lo)
        if self.typ == TYPE_RUN:
            # Clip every run to [start, end): sum of positive overlaps.
            s = self.data[:, 0].astype(np.int64)
            l = self.data[:, 1].astype(np.int64)
            overlap = np.minimum(l, end - 1) - np.maximum(s, start) + 1
            return int(np.maximum(overlap, 0).sum())
        # Popcount whole words, masking the partial edge words.
        end = min(end, CONTAINER_WIDTH)
        if end <= start:
            return 0
        w0, w1 = start >> 6, (end - 1) >> 6
        words = self.data[w0 : w1 + 1].copy()
        lo_bits = start & 63
        hi_bits = (end - 1) & 63
        if lo_bits:
            words[0] &= ~np.uint64(0) << np.uint64(lo_bits)
        if hi_bits != 63:
            words[-1] &= ~np.uint64(0) >> np.uint64(63 - hi_bits)
        return int(np.bitwise_count(words).sum())

    # -- mutators (return new container) ---------------------------------

    def with_bit(self, v: int) -> "Container":
        if self.contains(v):
            return self
        if self.typ == TYPE_RUN:
            return self._unrun().with_bit(v)
        if self.typ == TYPE_ARRAY:
            i = int(np.searchsorted(self.data, np.uint16(v)))
            arr = np.insert(self.data, i, np.uint16(v))
            if arr.size > ARRAY_MAX_SIZE:
                return Container(TYPE_BITMAP, _as_bitmap_words(arr), int(arr.size))
            return Container(TYPE_ARRAY, arr, int(arr.size))
        words = self.data.copy()
        words[v >> 6] |= np.uint64(1) << np.uint64(v & 63)
        return Container(TYPE_BITMAP, words, self._n + 1)

    def without_bit(self, v: int) -> "Container":
        if not self.contains(v):
            return self
        if self.typ == TYPE_RUN:
            return self._unrun().without_bit(v)
        if self.typ == TYPE_ARRAY:
            i = int(np.searchsorted(self.data, np.uint16(v)))
            return Container(TYPE_ARRAY, np.delete(self.data, i), self._n - 1)
        words = self.data.copy()
        words[v >> 6] &= ~(np.uint64(1) << np.uint64(v & 63))
        return Container.from_bitmap_words(words, self._n - 1)

    def with_many(self, vs: np.ndarray) -> "Container":
        """Union with a sorted-or-not uint16 position array."""
        if vs.size == 0:
            return self
        if self.typ == TYPE_RUN:
            # Run-native when the result can stay RLE; a scattered batch
            # (run count ~ size) goes through the materialized kernels
            # instead (see _runs_could_win).
            vs_u = np.unique(vs.astype(np.uint16))
            vs_runs = _positions_to_runs(vs_u)
            if _runs_could_win(
                self.data.shape[0] + vs_runs.shape[0], self._n + vs_u.size
            ):
                return Container.from_runs(
                    _union_runs(self._i64_runs(), vs_runs)
                )
            return self._unrun().with_many(vs_u)
        if self.typ == TYPE_ARRAY:
            # _sorted_union's stable radix sort + adjacent-dedup handles
            # unsorted/duplicated vs directly — no np.unique pre-sort.
            arr = _sorted_union(self.data, vs.astype(np.uint16))
            return Container.from_positions(arr)
        words = self.data.copy()
        np.bitwise_or.at(words, vs >> 6, np.uint64(1) << (vs.astype(np.uint64) & np.uint64(63)))
        return Container.from_bitmap_words(words)

    def without_many(self, vs: np.ndarray) -> "Container":
        if vs.size == 0:
            return self
        if self.typ == TYPE_RUN:
            vs_u = np.unique(vs.astype(np.uint16))
            vs_runs = _positions_to_runs(vs_u)
            # Removal can only add as many runs as removed spans; same
            # could-win gate as with_many keeps scattered batches on the
            # vectorized kernels.
            if _runs_could_win(
                self.data.shape[0] + vs_runs.shape[0], self._n
            ):
                return Container.from_runs(
                    _difference_runs(self._i64_runs(), vs_runs)
                )
            return self._unrun().without_many(vs_u)
        if self.typ == TYPE_ARRAY:
            # The membership table is duplicate- and order-insensitive.
            keep = ~_sorted_member_mask(self.data, vs.astype(np.uint16))
            arr = self.data[keep]
            return Container(TYPE_ARRAY, arr, int(arr.size))
        mask = np.zeros(BITMAP_N, dtype=np.uint64)
        np.bitwise_or.at(mask, vs >> 6, np.uint64(1) << (vs.astype(np.uint64) & np.uint64(63)))
        return Container.from_bitmap_words(self.data & ~mask)

    # -- set algebra -----------------------------------------------------
    # run×run and run×array compute ON the runs (reference's run-aware
    # op matrix, roaring.go:2599-2790); run×bitmap intersect verbs AND
    # the bitmap words against a cumsum-built run coverage mask (no
    # _unrun() materialization — ISSUE r7 satellite); the remaining
    # run×bitmap verbs materialize (union/xor outputs have no run
    # structure to preserve when one side is a dense bitmap).

    def intersect(self, other: "Container") -> "Container":
        if self.typ == TYPE_RUN and other.typ == TYPE_RUN:
            return Container.from_runs(
                _intersect_runs(self._i64_runs(), other._i64_runs())
            )
        if self.typ == TYPE_RUN and other.typ == TYPE_ARRAY:
            keep = _runs_member_mask(self.data, other.data)
            return Container(TYPE_ARRAY, other.data[keep], None)
        if self.typ == TYPE_ARRAY and other.typ == TYPE_RUN:
            keep = _runs_member_mask(other.data, self.data)
            return Container(TYPE_ARRAY, self.data[keep], None)
        if self.typ == TYPE_RUN or other.typ == TYPE_RUN:
            # run x bitmap (VERDICT r5 missing #2): AND the bitmap words
            # against a cumsum-built run coverage mask instead of
            # _unrun()-materializing the run side — the time-quantum x
            # standard-view pair's hot combination.
            run_c, bm_c = (self, other) if self.typ == TYPE_RUN else (other, self)
            return Container.from_bitmap_words(
                _runs_to_bitmap_words(run_c.data) & bm_c.data
            )
        a, b = self, other
        if a.typ == TYPE_ARRAY and b.typ == TYPE_ARRAY:
            if a.data.size > b.data.size:
                a, b = b, a  # search the smaller array in the larger
            return Container.from_positions(
                a.data[_sorted_member_mask(a.data, b.data)]
            )
        if a.typ == TYPE_ARRAY:
            a, b = b, a
        if b.typ == TYPE_ARRAY:  # bitmap ∩ array
            keep = (a.data[b.data >> 6] >> (b.data.astype(np.uint64) & np.uint64(63))) & np.uint64(1)
            return Container(TYPE_ARRAY, b.data[keep == 1], None)
        return Container.from_bitmap_words(a.data & b.data)

    def intersection_count(self, other: "Container") -> int:
        if self.typ == TYPE_RUN and other.typ == TYPE_RUN:
            r = _intersect_runs(self._i64_runs(), other._i64_runs())
            return int((r[:, 1] - r[:, 0] + 1).sum()) if r.size else 0
        if self.typ == TYPE_RUN and other.typ == TYPE_ARRAY:
            return int(_runs_member_mask(self.data, other.data).sum())
        if self.typ == TYPE_ARRAY and other.typ == TYPE_RUN:
            return int(_runs_member_mask(other.data, self.data).sum())
        if self.typ == TYPE_RUN or other.typ == TYPE_RUN:
            # run x bitmap: popcount over the masked words directly — no
            # materialized intermediate container at all.
            run_c, bm_c = (self, other) if self.typ == TYPE_RUN else (other, self)
            return int(
                np.bitwise_count(
                    _runs_to_bitmap_words(run_c.data) & bm_c.data
                ).sum()
            )
        a, b = self, other
        if a.typ == TYPE_ARRAY and b.typ == TYPE_ARRAY:
            if a.data.size > b.data.size:
                a, b = b, a
            return int(_sorted_member_mask(a.data, b.data).sum())
        if a.typ == TYPE_ARRAY:
            a, b = b, a
        if b.typ == TYPE_ARRAY:
            keep = (a.data[b.data >> 6] >> (b.data.astype(np.uint64) & np.uint64(63))) & np.uint64(1)
            return int(keep.sum())
        return int(np.bitwise_count(a.data & b.data).sum())

    def union(self, other: "Container") -> "Container":
        if self.typ == TYPE_RUN and other.typ == TYPE_RUN:
            return Container.from_runs(
                _union_runs(self._i64_runs(), other._i64_runs())
            )
        if (self.typ == TYPE_RUN and other.typ == TYPE_ARRAY) or (
            self.typ == TYPE_ARRAY and other.typ == TYPE_RUN
        ):
            run_c, arr_c = (
                (self, other) if self.typ == TYPE_RUN else (other, self)
            )
            arr_runs = _positions_to_runs(arr_c.data)
            # Scattered arrays (run count ~ size) can't yield a runny
            # union: the vectorized kernels win (code review r5).
            if _runs_could_win(
                run_c.data.shape[0] + arr_runs.shape[0],
                run_c._n + arr_c._n,
            ):
                return Container.from_runs(
                    _union_runs(run_c._i64_runs(), arr_runs)
                )
        a, b = self._unrun(), other._unrun()
        if a.typ == TYPE_ARRAY and b.typ == TYPE_ARRAY:
            return Container.from_positions(_sorted_union(a.data, b.data))
        return Container.from_bitmap_words(a.bitmap_words() | b.bitmap_words())

    def difference(self, other: "Container") -> "Container":
        if self.typ == TYPE_RUN and other.typ == TYPE_RUN:
            return Container.from_runs(
                _difference_runs(self._i64_runs(), other._i64_runs())
            )
        if self.typ == TYPE_RUN and other.typ == TYPE_ARRAY:
            arr_runs = _positions_to_runs(other.data)
            # Same scattered-operand gate as with_many/union/xor: a
            # removal can split at most one run per removed span.
            if _runs_could_win(
                self.data.shape[0] + arr_runs.shape[0], self._n
            ):
                return Container.from_runs(
                    _difference_runs(self._i64_runs(), arr_runs)
                )
            return self._unrun().difference(other)
        if self.typ == TYPE_ARRAY and other.typ == TYPE_RUN:
            keep = ~_runs_member_mask(other.data, self.data)
            out = self.data[keep]
            return Container(TYPE_ARRAY, out, int(out.size))
        a, b = self._unrun(), other._unrun()
        if a.typ == TYPE_ARRAY:
            if b.typ == TYPE_ARRAY:
                out = a.data[~_sorted_member_mask(a.data, b.data)]
            else:
                keep = (b.data[a.data >> 6] >> (a.data.astype(np.uint64) & np.uint64(63))) & np.uint64(1)
                out = a.data[keep == 0]
            return Container(TYPE_ARRAY, out.astype(np.uint16), int(out.size))
        return Container.from_bitmap_words(a.data & ~b.bitmap_words())

    def xor(self, other: "Container") -> "Container":
        run_pair = (
            self.typ == TYPE_RUN and other.typ in (TYPE_RUN, TYPE_ARRAY)
        ) or (self.typ == TYPE_ARRAY and other.typ == TYPE_RUN)
        if run_pair:
            ra = (
                self._i64_runs()
                if self.typ == TYPE_RUN
                else _positions_to_runs(self.data)
            )
            rb = (
                other._i64_runs()
                if other.typ == TYPE_RUN
                else _positions_to_runs(other.data)
            )
            # Same scattered-operand gate as union (code review r5),
            # sized per ADVICE r5. The provable bound is ra+rb output
            # runs (an xor membership toggle needs an operand toggle;
            # ≤2(ra+rb) toggles → ≤ra+rb runs, achieved when one
            # operand's runs split the other's), so 2*(ra+rb) carries a
            # deliberate 2x margin: marginal operand pairs route to the
            # vectorized kernels, the direction the r5 perf fix chose
            # after the scattered-operand run sweep measured ~90x slow.
            if _runs_could_win(
                2 * (ra.shape[0] + rb.shape[0]), self._n + other._n
            ):
                # (a\b) and (b\a) are disjoint; their union coalesces
                # any adjacency the symmetric difference re-creates.
                return Container.from_runs(
                    _union_runs(
                        _difference_runs(ra, rb), _difference_runs(rb, ra)
                    )
                )
        a, b = self._unrun(), other._unrun()
        if a.typ == TYPE_ARRAY and b.typ == TYPE_ARRAY:
            return Container.from_positions(np.setxor1d(a.data, b.data, assume_unique=True))
        return Container.from_bitmap_words(a.bitmap_words() ^ b.bitmap_words())

    def flip(self) -> "Container":
        """Complement within the container (reference flipBitmap)."""
        return Container.from_bitmap_words(~self.bitmap_words())

    def shift_left_one(self) -> tuple["Container", bool]:
        """Shift all positions up by one; returns (container, carry-out).

        Mirrors reference roaring/roaring.go Shift (:946): a bit at 0xffff
        carries into the next container's bit 0.
        """
        pos = self.positions().astype(np.int32) + 1
        carry = bool(pos.size and pos[-1] == CONTAINER_WIDTH)
        pos = pos[pos < CONTAINER_WIDTH]
        return Container.from_positions(pos.astype(np.uint16)), carry


class Bitmap:
    """64-bit roaring bitmap: sorted map of container key -> Container.

    reference roaring/roaring.go:145. Containers are kept in a dict with a
    lazily maintained sorted key list (the reference offers slice- and
    btree-backed Containers implementations, roaring/containers_slice.go,
    containers_btree.go; a dict+sorted-keys is the idiomatic Python
    equivalent with the same O(log n) seek / O(1) hit behavior).
    """

    __slots__ = ("_cs", "_keys", "_keys_gen", "_keys_built", "op_writer",
                 "op_n", "flags")

    def __init__(self, values: Optional[Iterable[int]] = None):
        self._cs: dict[int, Container] = {}
        self._keys: list[int] = []
        # Key-list freshness is a GENERATION pair, not a dirty bool: a
        # locked writer racing an UNLOCKED reader's lazy rebuild (stack
        # pack under churn) could otherwise lose its dirty mark — reader
        # sorts, writer inserts + sets dirty, reader stores its stale
        # sort AND clears the flag — and the missing container would
        # survive every _pack_confirmed retry (exec/tpu.py), silently
        # breaking the host tables' exactness invariant.
        self._keys_gen = 0     # bumped by every container insert/delete
        self._keys_built = 0   # generation the cached sort was built at
        # Durability hook: fragment storage attaches a WAL writer here
        # (reference fragment.go:455 attaches the op writer; ops appended at
        # roaring/roaring.go:1612). None means no-op.
        self.op_writer = None
        self.op_n = 0
        self.flags = 0
        if values is not None:
            vals = np.asarray(list(values) if not isinstance(values, np.ndarray) else values, dtype=np.uint64)
            if vals.size:
                self.add_many(vals, log=False)

    # -- key bookkeeping -------------------------------------------------

    def keys(self) -> list[int]:
        if self._keys_gen != self._keys_built:
            # Read the generation BEFORE snapshotting: a writer landing
            # mid-sort bumps _keys_gen past `g`, so the cache stays
            # marked stale and the next call re-sorts. sorted(dict) is
            # a single GIL-atomic C snapshot for int keys (no Python
            # callbacks), so the sort itself cannot tear.
            g = self._keys_gen
            # lint: allow-shared-state(documented lock-free rebuild: the generation check above keeps a torn snapshot marked stale so the next reader re-sorts)
            self._keys = sorted(self._cs)
            # lint: allow-shared-state(publish ordered after the rebuild under program order; a racing writer bumps _keys_gen past g and the cache stays stale)
            self._keys_built = g
        return self._keys

    def container(self, key: int) -> Optional[Container]:
        return self._cs.get(key)

    def _put(self, key: int, c: Container) -> None:
        if PARANOIA:
            c.validate(key)
        if c.n == 0:
            if key in self._cs:
                # lint: allow-shared-state(a Bitmap is confined to its owning Fragment: every mutating path holds Fragment.lock; lock-free query readers follow the snapshot contract)
                del self._cs[key]
                # lint: allow-shared-state(generation RMW runs under the owning Fragment.lock with the mutation it stamps; unlocked keys readers only ever observe staleness)
                self._keys_gen += 1
            return
        is_new = key not in self._cs
        self._cs[key] = c
        if is_new:
            # Mutate-then-bump, matching the delete path above: bumping
            # BEFORE the insert would let an unlocked keys() rebuild
            # capture the post-bump generation with a pre-insert
            # snapshot and mark it fresh — the lost-staleness race the
            # generation counter exists to prevent.
            self._keys_gen += 1

    def put_container(self, key: int, c: Container) -> None:
        self._put(key, c)

    # -- basic ops -------------------------------------------------------

    def add(self, v: int, log: bool = True) -> bool:
        """DirectAdd + op-log append (reference roaring/roaring.go DirectAdd/Add)."""
        key, low = v >> 16, v & 0xFFFF
        c = self._cs.get(key)
        if c is None:
            self._put(key, Container(TYPE_ARRAY, np.array([low], dtype=np.uint16), 1))
            changed = True
        else:
            nc = c.with_bit(low)
            if nc is c:
                changed = False
            else:
                self._put(key, nc)
                changed = True
        if changed and log and self.op_writer is not None:
            self.op_writer.append_add(v)
            self.op_n += 1
        return changed

    def remove(self, v: int, log: bool = True) -> bool:
        key, low = v >> 16, v & 0xFFFF
        c = self._cs.get(key)
        if c is None:
            return False
        nc = c.without_bit(low)
        if nc is c:
            return False
        self._put(key, nc)
        if log and self.op_writer is not None:
            self.op_writer.append_remove(v)
            self.op_n += 1
        return True

    @staticmethod
    def from_sorted_array(vs: np.ndarray) -> "Bitmap":
        """Bulk-build from SORTED-UNIQUE uint64 values, skipping the
        np.unique re-sort add_many pays (ISSUE r14: the vectorized slab
        decode emits sorted output already — the Roaring reference's
        word-level bulk path). One container constructed per key group,
        no per-value work; copies each lows slice so the source buffer
        is never pinned."""
        bm = Bitmap()
        v = np.ascontiguousarray(vs, dtype=np.uint64)
        if v.size == 0:
            return bm
        keys = v >> np.uint64(16)
        lows = (v & np.uint64(0xFFFF)).astype(np.uint16)
        boundaries = np.nonzero(np.diff(keys))[0] + 1
        starts = np.concatenate(([0], boundaries))
        ends = np.concatenate((boundaries, [keys.size]))
        for s, e in zip(starts, ends):
            cnt = int(e - s)
            chunk = lows[s:e]
            if cnt <= ARRAY_MAX_SIZE:
                c = Container(TYPE_ARRAY, chunk.copy(), cnt)
            else:
                c = Container(TYPE_BITMAP, _as_bitmap_words(chunk), cnt)
            bm._put(int(keys[s]), c)
        return bm

    def add_many(self, vs: np.ndarray, log: bool = True) -> int:
        """Batch add; one AddBatch op-log record (reference DirectAddN)."""
        vs = np.asarray(vs, dtype=np.uint64)
        if vs.size == 0:
            return 0
        # ONE global value sort + dedup: keys come out grouped AND each
        # group's lows sorted+unique, so the per-container O(n log n)
        # np.unique disappears (import was sort-bound; the reference's
        # DirectAddN gets pre-sorted input from importPositions too,
        # fragment.go:2053).
        sv = np.unique(vs)
        keys = sv >> np.uint64(16)
        lows = (sv & np.uint64(0xFFFF)).astype(np.uint16)
        boundaries = np.nonzero(np.diff(keys))[0] + 1
        starts = np.concatenate(([0], boundaries))
        ends = np.concatenate((boundaries, [keys.size]))
        changed = 0
        for s, e in zip(starts, ends):
            changed += self._merge_lows(int(keys[s]), lows[s:e])
        if changed and log and self.op_writer is not None:
            # opN counts mutated values like the reference's op.count()
            # (roaring.go:1620), so it matches what a WAL replay computes.
            self.op_writer.append_add_batch(vs)
            # lint: allow-shared-state(op_n RMW is fragment-confined: every WAL-logged write path holds the owning Fragment.lock)
            self.op_n += int(vs.size)
        return changed

    def _merge_lows(self, key: int, chunk: np.ndarray) -> int:
        """Union one container's sorted-unique lows; returns bits added."""
        c = self._cs.get(key)
        if c is None:
            # Copy: from_positions would store the slice VIEW, pinning
            # the whole batch's lows buffer for the container's life.
            nc = Container.from_positions(chunk.copy())
        else:
            nc = c.with_many(chunk)
        self._put(key, nc)
        return nc.n - (c.n if c is not None else 0)

    def import_container_groups(
        self, keys: np.ndarray, counts: np.ndarray, lows: np.ndarray
    ) -> int:
        """Container-granular union (reference ImportRoaringBits,
        roaring/roaring.go:1511): pre-grouped sorted-unique lows per key
        (native.import_containers output) merge one container at a time —
        no per-value work, no comparison sort. Returns bits added.
        Op-logging is the caller's job (it holds the positions).

        OWNERSHIP: fresh containers keep zero-copy views of `lows`, so
        the caller must hand over an owned buffer it will not reuse
        (native.import_containers allocates one per call)."""
        changed = 0
        off = 0
        for j in range(keys.size):
            cnt = int(counts[j])
            key = int(keys[j])
            chunk = lows[off : off + cnt]
            c = self._cs.get(key)
            if c is None:
                if cnt <= ARRAY_MAX_SIZE:
                    nc = Container(TYPE_ARRAY, chunk, cnt)
                else:
                    nc = Container(TYPE_BITMAP, _as_bitmap_words(chunk), cnt)
                self._put(key, nc)
                changed += cnt
            else:
                nc = c.with_many(chunk)
                self._put(key, nc)
                changed += nc.n - c.n
            off += cnt
        return changed

    def remove_many(self, vs: np.ndarray, log: bool = True) -> int:
        vs = np.asarray(vs, dtype=np.uint64)
        if vs.size == 0:
            return 0
        sv = np.unique(vs)  # see add_many: grouped keys + sorted lows
        keys = sv >> np.uint64(16)
        lows = (sv & np.uint64(0xFFFF)).astype(np.uint16)
        boundaries = np.nonzero(np.diff(keys))[0] + 1
        starts = np.concatenate(([0], boundaries))
        ends = np.concatenate((boundaries, [keys.size]))
        changed = 0
        for s, e in zip(starts, ends):
            key = int(keys[s])
            c = self._cs.get(key)
            if c is not None:
                nc = c.without_many(lows[s:e])
                changed += c.n - nc.n
                self._put(key, nc)
        if changed and log and self.op_writer is not None:
            self.op_writer.append_remove_batch(vs)
            self.op_n += int(vs.size)
        return changed

    def optimize(self) -> int:
        """Re-pack containers as RLE runs where that is the smallest
        encoding (reference roaring.go Optimize). Batch mutators and
        run/array set algebra are run-preserving since r5; point
        mutators (with_bit/without_bit) and bitmap-side ops still leave
        array/bitmap results, so long-lived runny fragments call this
        after point-write churn to reclaim host RAM. Returns the number
        of containers converted."""
        converted = 0
        for key in self.keys():
            c = self._cs[key]
            if c.typ == TYPE_RUN:
                continue
            runs = c.runs()
            if _runs_win(runs.shape[0], c.n):
                self._cs[key] = Container(
                    TYPE_RUN, runs.astype(np.uint16), c.n
                )
                converted += 1
        return converted

    def contains(self, v: int) -> bool:
        c = self._cs.get(v >> 16)
        return c is not None and c.contains(v & 0xFFFF)

    def count(self) -> int:
        return sum(c.n for c in self._cs.values())

    def any(self) -> bool:
        return any(c.n for c in self._cs.values())

    def count_range(self, start: int, end: int) -> int:
        """Count of bits in [start, end) (reference roaring.go:438)."""
        if end <= start:
            return 0
        skey, ekey = start >> 16, (end - 1) >> 16
        total = 0
        ks = self.keys()
        i = bisect.bisect_left(ks, skey)
        while i < len(ks) and ks[i] <= ekey:
            key = ks[i]
            c = self._cs[key]
            lo = start - (key << 16) if key == skey else 0
            hi = end - (key << 16) if key == ekey else CONTAINER_WIDTH
            if lo <= 0 and hi >= CONTAINER_WIDTH:
                total += c.n
            else:
                total += c.count_range(max(lo, 0), hi)
            i += 1
        return total

    def min(self) -> tuple[int, bool]:
        for key in self.keys():
            c = self._cs[key]
            if c.n:
                return (key << 16) | int(c.positions()[0]), True
        return 0, False

    def max(self) -> int:
        for key in reversed(self.keys()):
            c = self._cs[key]
            if c.n:
                return (key << 16) | int(c.positions()[-1])
        return 0

    def to_array(self) -> np.ndarray:
        """All set bits as a sorted uint64 array."""
        parts = []
        for key in self.keys():
            c = self._cs[key]
            if c.n:
                parts.append((np.uint64(key) << np.uint64(16)) | c.positions().astype(np.uint64))
        if not parts:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(parts)

    def __iter__(self) -> Iterator[int]:
        return iter(self.to_array().tolist())

    def iterate_from(self, start: int) -> Iterator[int]:
        arr = self.to_array()
        i = np.searchsorted(arr, np.uint64(start), side="left")
        return iter(arr[i:].tolist())

    # -- set algebra -----------------------------------------------------

    def _binary(self, other: "Bitmap", fn, keys: Iterable[int]) -> "Bitmap":
        out = Bitmap()
        empty = Container.empty()
        for key in keys:
            a = self._cs.get(key, empty)
            b = other._cs.get(key, empty)
            out._put(key, fn(a, b))
        return out

    def intersect(self, other: "Bitmap") -> "Bitmap":
        keys = self._cs.keys() & other._cs.keys()
        out = Bitmap()
        for key in keys:
            out._put(key, self._cs[key].intersect(other._cs[key]))
        return out

    def intersection_count(self, other: "Bitmap") -> int:
        keys = self._cs.keys() & other._cs.keys()
        # Array-array pairs batch into ONE native sorted-merge call per
        # row pair (reference intersectionCountArrayArray,
        # roaring/roaring.go:570) — the per-container Python dispatch was
        # the CPU executor's dominant cost at bench density; other type
        # pairs take the per-container path.
        aa_a: list[np.ndarray] = []
        aa_b: list[np.ndarray] = []
        total = 0
        for k in keys:
            ca, cb = self._cs[k], other._cs[k]
            if ca.typ == TYPE_ARRAY and cb.typ == TYPE_ARRAY:
                aa_a.append(ca.data)
                aa_b.append(cb.data)
            else:
                total += ca.intersection_count(cb)
        if aa_a:
            from pilosa_tpu import native

            n = native.intersection_count_many(aa_a, aa_b)
            if n is None:
                n = sum(
                    int(_sorted_member_mask(a, b).sum())
                    for a, b in zip(aa_a, aa_b)
                )
            total += n
        return total

    def union(self, other: "Bitmap") -> "Bitmap":
        return self._binary(other, Container.union, self._cs.keys() | other._cs.keys())

    def union_in_place(self, other: "Bitmap") -> None:
        for key, b in other._cs.items():
            a = self._cs.get(key)
            self._put(key, b if a is None else a.union(b))

    def difference(self, other: "Bitmap") -> "Bitmap":
        out = Bitmap()
        for key, a in self._cs.items():
            b = other._cs.get(key)
            out._put(key, a if b is None else a.difference(b))
        return out

    def xor(self, other: "Bitmap") -> "Bitmap":
        return self._binary(other, Container.xor, self._cs.keys() | other._cs.keys())

    def shift(self) -> "Bitmap":
        """Shift all bits up by one (reference roaring.go:946 Shift(1))."""
        out = Bitmap()
        carries: dict[int, bool] = {}
        for key in self.keys():
            c, carry = self._cs[key].shift_left_one()
            out._put(key, c)
            if carry:
                carries[key + 1] = True
        for key in carries:
            c = out._cs.get(key)
            one = Container(TYPE_ARRAY, np.array([0], dtype=np.uint16), 1)
            out._put(key, one if c is None else c.with_bit(0))
        return out

    def flip(self, start: int, end: int) -> "Bitmap":
        """Complement of bits in [start, end] inclusive (reference :1683)."""
        out = self.clone()
        for key in range(start >> 16, (end >> 16) + 1):
            lo = max(start - (key << 16), 0)
            hi = min(end - (key << 16), CONTAINER_WIDTH - 1)
            mask = np.zeros(CONTAINER_WIDTH, dtype=bool)
            mask[lo : hi + 1] = True
            mask_words = np.packbits(mask, bitorder="little").view(np.uint64)
            c = out._cs.get(key)
            words = c.bitmap_words() ^ mask_words if c is not None else mask_words
            out._put(key, Container.from_bitmap_words(words))
        return out

    def offset_range(self, offset: int, start: int, end: int) -> "Bitmap":
        """Bits in [start, end) re-based to offset (reference roaring.go:537).

        All three arguments must be container-aligned (multiples of 2^16) —
        same contract as the reference. Containers are shared, not copied.
        """
        assert offset & 0xFFFF == 0 and start & 0xFFFF == 0 and end & 0xFFFF == 0
        off_key, s_key, e_key = offset >> 16, start >> 16, end >> 16
        out = Bitmap()
        ks = self.keys()
        i = bisect.bisect_left(ks, s_key)
        while i < len(ks) and ks[i] < e_key:
            out._put(off_key + (ks[i] - s_key), self._cs[ks[i]])
            i += 1
        return out

    def clone(self) -> "Bitmap":
        out = Bitmap()
        out._cs = dict(self._cs)
        out._keys_gen = 1  # fresh instance: built==0 != gen -> re-sort
        return out

    # -- import (bulk union/clear from serialized roaring) ----------------

    def import_roaring_bits(self, data: bytes, clear: bool = False, log: bool = True, parsed: Optional["Bitmap"] = None) -> int:
        """Union (or clear) a serialized roaring bitmap into self in one op.

        reference roaring/roaring.go:1511 ImportRoaringBits; logged as a
        single AddRoaring/RemoveRoaring op (reference fragment.go:2255).
        Returns the number of bits changed. `parsed` lets a caller that
        already deserialized `data` (fragment.import_roaring reads the
        container keys for epoch stamping) skip the second parse; it
        must be the deserialization of `data` — the WAL still logs the
        raw bytes.
        """
        from pilosa_tpu.roaring.codec import deserialize

        other = parsed if parsed is not None else deserialize(data)
        changed = 0
        for key, b in other._cs.items():
            a = self._cs.get(key)
            if clear:
                if a is None:
                    continue
                nc = a.difference(b)
                changed += a.n - nc.n
                self._put(key, nc)
            else:
                if a is None:
                    changed += b.n
                    self._put(key, b)
                else:
                    nc = a.union(b)
                    changed += nc.n - a.n
                    self._put(key, nc)
        if changed and log and self.op_writer is not None:
            self.op_writer.append_roaring(data, changed, clear)
            self.op_n += changed
        return changed

    # -- serialization glue (implemented in codec.py) ---------------------

    def to_bytes(self) -> bytes:
        from pilosa_tpu.roaring.codec import serialize

        return serialize(self)

    @staticmethod
    def from_bytes(data: bytes) -> "Bitmap":
        from pilosa_tpu.roaring.codec import deserialize

        return deserialize(data)

    def __eq__(self, other) -> bool:
        if not isinstance(other, Bitmap):
            return NotImplemented
        return np.array_equal(self.to_array(), other.to_array())

    def __repr__(self) -> str:
        return f"Bitmap(count={self.count()}, containers={len(self._cs)})"
