"""64-bit Roaring bitmaps, numpy-vectorized.

Re-design of the reference's roaring package (reference roaring/roaring.go):
a Bitmap maps 48-bit container keys to 2^16-bit containers. In memory a
container is either a sorted uint16 array or a 1024-word uint64 bitmap —
run containers exist only in the serialized form (they are converted on read
and re-detected by Optimize-equivalent logic on write, mirroring the effect of
reference roaring/roaring.go Optimize). All container ops are vectorized
numpy; the hot query path does not run per-bit Python loops.

The serialized form is byte-compatible with the reference's Pilosa roaring
file format (magic 12348, reference roaring/roaring.go:30-45,
docs/architecture.md) including the appended op log, so data directories
written by the Go reference load here and vice versa.
"""

from pilosa_tpu.roaring.bitmap import (
    ARRAY_MAX_SIZE,
    BITMAP_N,
    CONTAINER_WIDTH,
    Bitmap,
    Container,
)
from pilosa_tpu.roaring.codec import (
    MAGIC_NUMBER,
    deserialize,
    serialize,
    serialized_size,
)
