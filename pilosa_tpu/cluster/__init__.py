"""Cluster layer: topology, peer RPC, scatter-gather, resize, anti-entropy.

The reference's distribution model (SURVEY.md §2.2): the column space is
cut into 2^20-wide shards, shards hash to one of 256 partitions
(fnv64a(index, shard) % 256, reference cluster.go:871), partitions map to
a ring offset via jump-consistent-hash (cluster.go:947), and ReplicaN
consecutive ring nodes own each partition. Queries scatter shards to
owning nodes and stream-reduce; writes fan out to every replica.

Here the intra-host parallelism is the TPU mesh (pilosa_tpu.parallel);
this package is the DCN plane across hosts.
"""

from pilosa_tpu.cluster.topology import (
    URI,
    Node,
    Topology,
    JmpHasher,
    ModHasher,
    STATE_STARTING,
    STATE_NORMAL,
    STATE_DEGRADED,
    STATE_RESIZING,
)
from pilosa_tpu.cluster.cluster import Cluster
from pilosa_tpu.cluster.client import InternalClient, ClientError
from pilosa_tpu.cluster.broadcast import (
    Message,
    NopBroadcaster,
    HTTPBroadcaster,
)

__all__ = [
    "URI",
    "Node",
    "Topology",
    "JmpHasher",
    "ModHasher",
    "Cluster",
    "InternalClient",
    "ClientError",
    "Message",
    "NopBroadcaster",
    "HTTPBroadcaster",
    "STATE_STARTING",
    "STATE_NORMAL",
    "STATE_DEGRADED",
    "STATE_RESIZING",
]
