"""Control-plane broadcast messages (reference broadcast.go:30-140).

The reference encodes 16 protobuf message types with a 1-byte type prefix
and delivers them sync (HTTP POST /internal/cluster/message to every
node, server.go:666) or async (piggybacked on gossip). Here messages are
JSON objects with a "type" field — the control plane is low-rate schema/
topology traffic, so self-describing JSON beats protobuf for
debuggability; the data plane (imports, fragments) stays binary.
"""

from __future__ import annotations

import threading
from typing import Optional, Protocol

# Message types (reference broadcast.go:55-122).
MSG_CREATE_SHARD = "create-shard"
MSG_CREATE_INDEX = "create-index"
MSG_DELETE_INDEX = "delete-index"
MSG_CREATE_FIELD = "create-field"
MSG_DELETE_FIELD = "delete-field"
MSG_DELETE_AVAILABLE_SHARD = "delete-available-shard"
MSG_CLUSTER_STATUS = "cluster-status"
MSG_RESIZE_INSTRUCTION = "resize-instruction"
MSG_RESIZE_COMPLETE = "resize-complete"
MSG_SET_COORDINATOR = "set-coordinator"
MSG_UPDATE_COORDINATOR = "update-coordinator"
MSG_NODE_EVENT = "node-event"
MSG_NODE_STATE = "node-state"
MSG_NODE_STATUS = "node-status"
MSG_RECALCULATE_CACHES = "recalculate-caches"
MSG_RESIZE_ABORT = "resize-abort"
# Coordinator liveness while a resize job is in flight (ISSUE r9):
# followers renew their rollback lease on each one; when the coordinator
# dies the heartbeats die with it and every follower's lease expires.
MSG_RESIZE_HEARTBEAT = "resize-heartbeat"

# Node events (reference event.go).
EVENT_JOIN = "join"
EVENT_LEAVE = "leave"
EVENT_UPDATE = "update"


class Message(dict):
    """A typed control message; plain dict with a required 'type'.

    The wire representation goes through the module serializer seam
    (reference encoding/proto Serializer, proto.go:29-42): typed binary
    protobuf frames for registered control messages
    (cluster/private_wire.py), JSON for unregistered ones, and
    legacy-JSON sniffing on receive so mixed-version clusters
    interoperate."""

    @staticmethod
    def make(msg_type: str, **fields) -> "Message":
        m = Message(fields)
        m["type"] = msg_type
        return m

    def to_bytes(self) -> bytes:
        return _serializer().marshal(self)

    @staticmethod
    def from_bytes(data: bytes) -> "Message":
        return Message(_serializer().unmarshal(data))


_SERIALIZER = None


def _serializer():
    global _SERIALIZER
    if _SERIALIZER is None:
        import os

        from pilosa_tpu.cluster.private_wire import JSONSerializer, ProtoSerializer

        # PILOSA_TPU_CONTROL_WIRE=json keeps frames parseable by
        # JSON-only peers during a rolling upgrade (see private_wire.py
        # compatibility notes).
        if os.environ.get("PILOSA_TPU_CONTROL_WIRE", "").lower() == "json":
            _SERIALIZER = JSONSerializer()
        else:
            _SERIALIZER = ProtoSerializer()
    return _SERIALIZER


def set_serializer(s) -> None:
    """Swap the control-plane serializer (tests / wire-compat modes)."""
    global _SERIALIZER
    _SERIALIZER = s


class Broadcaster(Protocol):
    """reference broadcast.go:30 broadcaster interface."""

    def send_sync(self, msg: Message) -> None: ...
    def send_async(self, msg: Message) -> None: ...
    def send_to(self, node, msg: Message) -> None: ...
    def reset_wire_negotiation(self) -> None: ...


class NopBroadcaster:
    """Default no-op (reference broadcast.go:41) so single-node servers and
    tests need no cluster plumbing."""

    def send_sync(self, msg: Message) -> None:
        pass

    def send_async(self, msg: Message) -> None:
        pass

    def send_to(self, node, msg: Message) -> None:
        pass

    def reset_wire_negotiation(self) -> None:
        pass


class HTTPBroadcaster:
    """Delivers messages over the internal client to every peer
    (reference server.go SendSync :666).

    send_sync raises on the first failed peer; send_async fires
    best-effort threads (the gossip-queue analog — same at-most-once
    semantics from the sender's view).
    """

    def __init__(self, cluster, client=None):
        self.cluster = cluster
        from pilosa_tpu.cluster.client import InternalClient

        self.client = client or InternalClient()
        # Peers that rejected a binary frame and accepted the JSON retry:
        # JSON-only older builds mid-rolling-upgrade (ADVICE r3: the
        # binary default would otherwise require the operator to pre-set
        # PILOSA_TPU_CONTROL_WIRE=json on every sender). Subsequent sends
        # to them go straight to JSON (every receiver, old or new, parses
        # JSON — receive sniffs the frame). Cleared on membership change
        # (cluster.receive_message MSG_CLUSTER_STATUS) so a replaced or
        # upgraded-in-place node re-negotiates. Guarded by _wire_lock:
        # the fan-out send threads read/pin concurrently with the
        # message handler's membership-change clear (shared-state rule).
        self._json_peers: set[str] = set()
        self._wire_lock = threading.Lock()

    def _peers(self):
        local_id = self.cluster.local_node.id
        return [n for n in self.cluster.topology.nodes if n.id != local_id]

    def reset_wire_negotiation(self) -> None:
        """Forget per-peer wire pins (called by the cluster on membership
        change: a replaced or upgraded-in-place node may speak binary)."""
        with self._wire_lock:
            self._json_peers.clear()

    @staticmethod
    def _is_parse_failure(e) -> bool:
        """True when an HTTP error means 'the peer could not PARSE the
        frame' (safe to retry as JSON). Current peers answer a structured
        code='bad-frame' 400 before any side effect; legacy JSON-only
        builds surface json.JSONDecodeError through their panic trap, so
        the 500 body's final traceback line names the decoder. Anything
        else (a handler error AFTER the message was parsed and possibly
        partially applied) must NOT be retried — control messages are
        idempotent by design, but re-running a half-applied handler is
        still the sender guessing about receiver state. (Deliberately
        narrow: only the exception NAME is matched, because a panic-trap
        body carries a full traceback whose source lines could contain
        arbitrary function names.)"""
        if e.status < 400:
            return False
        if getattr(e, "code", "") == "bad-frame":
            return True
        return getattr(e, "code", "") == "" and "JSONDecodeError" in str(e)

    def _deliver(self, node, msg: Message, payload: Optional[bytes] = None) -> None:
        """Send with per-peer wire negotiation: a peer that answers a
        parse failure to the default (possibly binary) frame gets ONE
        retry with legacy JSON; success pins that peer to JSON. Transport
        failures (status 0: refused/timeout) are not retried — the frame
        never reached a parser. Broadcast paths pass the default payload
        in so an N-peer send marshals once, not N times."""
        from pilosa_tpu.cluster.client import ClientError
        from pilosa_tpu.cluster.private_wire import JSONSerializer

        from pilosa_tpu.utils.deadline import Deadline, deadline_scope

        node_id = getattr(node, "id", None)
        if payload is None:
            payload = msg.to_bytes()
        json_payload = None  # marshalled only on the fallback paths
        with self._wire_lock:
            pinned_json = node_id in self._json_peers
        if pinned_json:
            json_payload = JSONSerializer().marshal(msg)
            if json_payload == payload:
                json_payload = None  # already JSON: nothing to negotiate
            else:
                payload = json_payload
        # Budget per frame (deadline-scope rule): one delivery is at
        # most two wire attempts (default + JSON renegotiation), so 2x
        # the client timeout bounds the frame without squeezing the
        # fallback when the first attempt burned a full socket timeout.
        # An outer (tighter) request deadline still wins — scopes nest.
        # getattr: test doubles stand in for the client without a
        # timeout attribute.
        with deadline_scope(Deadline(getattr(self.client, "timeout", 30.0) * 2)):
            try:
                self.client.send_message(node, payload)
                return
            except ClientError as e:
                if not self._is_parse_failure(e):
                    raise
                if json_payload is None:
                    json_payload = JSONSerializer().marshal(msg)
                if json_payload == payload:
                    raise  # frame WAS JSON; nothing better to offer
            from pilosa_tpu.cluster.client import count_rpc_retry, peer_label

            count_rpc_retry(peer_label(node), "send_message")
            self.client.send_message(node, json_payload)
        if node_id is not None:
            with self._wire_lock:
                self._json_peers.add(node_id)

    def send_sync(self, msg: Message) -> None:
        peers = self._peers()
        if not peers:
            return
        payload = msg.to_bytes()  # marshal once for all peers
        errors: list[str] = []
        lock = threading.Lock()

        def send(node):
            try:
                self._deliver(node, msg, payload)
            except Exception as e:  # collected, not fatal per-peer
                with lock:
                    errors.append(f"{node.id}: {e}")

        # One RTT total, not N sequential RTTs.
        from pilosa_tpu.utils.threads import spawn

        threads = [
            spawn("cluster-broadcast", send, args=(n,), start=False)
            for n in peers
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError("broadcast failed: " + "; ".join(errors))

    def send_async(self, msg: Message) -> None:
        from pilosa_tpu.utils.threads import spawn

        payload = msg.to_bytes()  # marshal once for all peers
        for node in self._peers():
            spawn(
                "cluster-broadcast",
                self._send_quiet, args=(node, msg, payload),
            )

    def _send_quiet(self, node, msg: Message, payload: bytes) -> None:
        try:
            self._deliver(node, msg, payload)
        except Exception:
            # Async broadcast is best-effort by contract (missed nodes
            # reconverge via gossip/anti-entropy) — but a silently
            # diverging peer must still be visible on /metrics.
            from pilosa_tpu.utils.stats import global_stats

            global_stats.with_tags(f"peer:{node.id}").count(
                "broadcast_async_errors_total"
            )

    def send_to(self, node, msg: Message) -> None:
        self._deliver(node, msg)
