"""Per-peer circuit breaker for the DCN data plane (ISSUE r9 tentpole 2).

One breaker per peer (host:port — the same label every peer_rpc_* series
uses), owned by the InternalClient that dials that peer, NOT a module
global: in-process test clusters run many nodes in one interpreter, and
node A's view of peer C must never be poisoned by node B's one-sided
partition to C (the same asymmetry discipline as the failure detector's
vote_down).

State machine (the classic three-state breaker):

- CLOSED: traffic flows; consecutive transport failures count up.
  ``threshold`` consecutive failures -> OPEN.
- OPEN: routing layers (map_shards node selection, route_write*) treat
  the peer like NODE_STATE_DOWN and go straight to replicas instead of
  eating a socket timeout per request. The client itself never refuses a
  dial — the failure detector's probes and any sole-owner fallback must
  still reach the peer, and their outcomes drive recovery.
- After a jittered cooldown OPEN relaxes to HALF_OPEN: the peer is
  routable again, and the next real RPC is the probe. Success -> CLOSED;
  failure -> OPEN again with the cooldown doubled (capped), so a peer
  that keeps failing is re-probed at a decaying rate instead of a fixed
  hammer.

Failures are TRANSPORT failures only (refused, reset, timeout): an HTTP
error status means the peer is alive and serving — it closes the
breaker. A timeout induced by an almost-expired query deadline is the
query's fault, not the peer's; the client skips recording those
(client.py _do).

Metrics: ``peer_breaker_state{peer}`` gauge (0 closed, 1 half-open,
2 open) and ``peer_breaker_transitions_total{peer,to}``.
"""

from __future__ import annotations

import random
import threading
import time

STATE_CLOSED = "closed"
STATE_HALF_OPEN = "half-open"
STATE_OPEN = "open"

_STATE_GAUGE = {STATE_CLOSED: 0, STATE_HALF_OPEN: 1, STATE_OPEN: 2}


class _PeerBreaker:
    __slots__ = ("state", "failures", "reopen_count", "open_until")

    def __init__(self):
        self.state = STATE_CLOSED
        self.failures = 0  # consecutive transport failures
        self.reopen_count = 0  # consecutive OPEN entries (backoff exponent)
        self.open_until = 0.0  # monotonic instant the cooldown ends


class BreakerRegistry:
    def __init__(
        self,
        threshold: int = 3,
        cooldown: float = 1.0,
        max_cooldown: float = 30.0,
    ):
        self.threshold = max(int(threshold), 1)
        self.cooldown = float(cooldown)
        self.max_cooldown = float(max_cooldown)
        self._lock = threading.Lock()
        self._peers: dict[str, _PeerBreaker] = {}

    # -- transitions (lock held) -------------------------------------------

    def _publish(self, peer: str, b: _PeerBreaker, to_state: str) -> None:
        from pilosa_tpu.utils.stats import global_stats

        b.state = to_state
        global_stats.with_tags(f"peer:{peer}").gauge(
            "peer_breaker_state", _STATE_GAUGE[to_state]
        )
        global_stats.with_tags(f"peer:{peer}", f"to:{to_state}").count(
            "peer_breaker_transitions_total"
        )

    def _open(self, peer: str, b: _PeerBreaker) -> None:
        # Jittered exponential cooldown: 0.5-1.5x the doubled base, so a
        # fleet of coordinators that all saw the same peer die does not
        # re-probe it in lockstep.
        base = min(self.cooldown * (2**b.reopen_count), self.max_cooldown)
        b.reopen_count += 1
        b.open_until = time.monotonic() + base * (0.5 + random.random())
        self._publish(peer, b, STATE_OPEN)

    # -- recording (called from client._do) --------------------------------

    def record_failure(self, peer: str) -> None:
        """One transport failure. HALF_OPEN probe failure re-opens with a
        doubled cooldown; threshold consecutive CLOSED failures open."""
        with self._lock:
            b = self._peers.setdefault(peer, _PeerBreaker())
            b.failures += 1
            if b.state == STATE_HALF_OPEN or (
                b.state == STATE_CLOSED and b.failures >= self.threshold
            ):
                self._open(peer, b)

    def record_success(self, peer: str) -> None:
        """Any completed exchange (including an HTTP error status: the
        peer answered) closes the breaker and resets the backoff."""
        with self._lock:
            b = self._peers.get(peer)
            if b is None:
                return
            b.failures = 0
            b.reopen_count = 0
            if b.state != STATE_CLOSED:
                self._publish(peer, b, STATE_CLOSED)

    # -- routing queries ----------------------------------------------------

    def is_blocked(self, peer: str) -> bool:
        """True while the peer's breaker is OPEN and inside its cooldown:
        routing layers treat the peer like DOWN. Cooldown expiry relaxes
        to HALF_OPEN here (the first caller to ask after expiry performs
        the state change; the next real RPC is the probe)."""
        with self._lock:
            b = self._peers.get(peer)
            if b is None or b.state == STATE_CLOSED:
                return False
            if b.state == STATE_OPEN:
                if time.monotonic() < b.open_until:
                    return True
                self._publish(peer, b, STATE_HALF_OPEN)
            return False  # HALF_OPEN: routable — the next RPC probes

    def state(self, peer: str) -> str:
        with self._lock:
            b = self._peers.get(peer)
            return b.state if b is not None else STATE_CLOSED
