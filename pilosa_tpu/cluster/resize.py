"""Cluster resize: elastic add/remove of nodes with fragment re-placement
(reference cluster.go:784-868 fragSources, :1196-1441 resizeJob /
followResizeInstruction, holder.go:1104 holderCleaner).

Flow (coordinator-driven state machine, reference cluster.go:47-50):

1. Coordinator receives add/remove (HTTP endpoint or a JOIN node event),
   snapshots the old topology, builds the new one, and diffs placement:
   for every (index, shard) a node owns in the NEW topology but not the
   OLD, an instruction entry points it at a surviving old owner.
2. State broadcasts to RESIZING (API writes 503 during the move), then
   each node gets a MSG_RESIZE_INSTRUCTION and fetches whole fragments
   over /internal/fragment/data (reference RetrieveShardFromURI
   http/client.go:742), unioning them into local storage.
3. Nodes report MSG_RESIZE_COMPLETE; when all have, the coordinator
   broadcasts the new node list with state NORMAL; every node then drops
   fragments it no longer owns (holderCleaner).
4. Abort (POST /cluster/resize/abort, reference api.go:1250) rolls state
   back to NORMAL on the old topology.
"""

from __future__ import annotations

import threading
from typing import Optional

from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.broadcast import Message
from pilosa_tpu.cluster.client import ClientError
from pilosa_tpu.cluster.topology import (
    NODE_STATE_DOWN,
    Node,
    STATE_NORMAL,
    STATE_RESIZING,
    Topology,
)
from pilosa_tpu.utils.logger import NopLogger
from pilosa_tpu.utils.stats import global_stats


class ResizeError(Exception):
    pass


class Resizer:
    """Owns resize jobs on the coordinator and instruction-following on
    every node. Installed via cluster.attach_resizer()."""

    #: Coordinator-side auto-abort: a job whose completions don't all
    #: arrive within this window rolls back instead of wedging the
    #: cluster in RESIZING (ADVICE r2: no manual-abort-only escape).
    job_timeout: float = 600.0

    def __init__(self, cluster, logger=None):
        self.cluster = cluster
        self.log = logger or NopLogger()
        self._lock = threading.RLock()
        self._job_id = 0
        # Coordinator-side live job state.
        self._active_job: Optional[int] = None
        self._pending_nodes: set[str] = set()
        self._new_nodes: Optional[list[Node]] = None
        self._notify_nodes: list[Node] = []
        self._timer: Optional[threading.Timer] = None
        # Set on every node while it should clean after the topology flips.
        self._needs_clean = False
        cluster.resizer = self

    # -- coordinator: job control (reference cluster.go:1196) --------------

    def add_node(self, node: Node) -> int:
        """Grow the cluster by one node; returns the job id."""
        with self._lock:
            if self.cluster.topology.node_by_id(node.id) is not None:
                raise ResizeError(f"node already in cluster: {node.id}")
            new_nodes = [
                Node(n.id, n.uri, n.is_coordinator, n.state)
                for n in self.cluster.topology.nodes
            ] + [Node(node.id, node.uri, False)]
            # lint: allow-lock-discipline(control plane: job mutations serialize across the announce RPCs by design; the data path never takes this lock)
            return self._start_job(new_nodes)

    def remove_node(self, node_id: str) -> int:
        with self._lock:
            gone = self.cluster.topology.node_by_id(node_id)
            if gone is None:
                raise ResizeError(f"node not in cluster: {node_id}")
            if gone.is_coordinator:
                raise ResizeError("cannot remove the coordinator")
            new_nodes = [
                Node(n.id, n.uri, n.is_coordinator, n.state)
                for n in self.cluster.topology.nodes
                if n.id != node_id
            ]
            # lint: allow-lock-discipline(control plane: job mutations serialize across the announce RPCs by design; the data path never takes this lock)
            return self._start_job(new_nodes, removed=gone)

    def handle_join(self, node: Node) -> None:
        """A JOIN node event on the coordinator triggers a grow job
        (reference listenForJoins cluster.go:1141)."""
        try:
            self.add_node(node)
        except ResizeError:
            # Two reasons land here. A resize job already running: do
            # nothing, the joiner keeps re-announcing. Already a member:
            # that is a RESTARTED --join node (ADVICE r3 medium) — it
            # boots single-node believing itself coordinator while the
            # cluster still routes shards to it, so re-send the current
            # schema + cluster status directly instead of silently
            # dropping the announce (reference nodeJoin re-sends
            # ClusterStatus to existing members, cluster.go:2121-2134).
            if self.cluster.topology.node_by_id(node.id) is None:
                return
            schema = (
                {"indexes": self.cluster.holder.schema()}
                if self.cluster.holder is not None
                else {}
            )
            status = Message.make(
                bc.MSG_CLUSTER_STATUS,
                state=self.cluster.state(),
                nodes=[n.to_json() for n in self.cluster.topology.nodes],
                replicaN=self.cluster.topology.replica_n,
            )
            try:
                self.cluster.broadcaster.send_to(
                    node,
                    Message.make(
                        bc.MSG_NODE_STATUS,
                        schema=schema,
                        # available shards too: the restarted node must
                        # fan queries out over every shard immediately,
                        # not after the next anti-entropy pass (the
                        # normal join path ships this in the resize
                        # instruction for the same reason).
                        available=self._available_map(),
                    ),
                )
                self.cluster.broadcaster.send_to(node, status)
            except Exception as e:  # noqa: BLE001 — joiner re-announces
                self.log.printf("resize: rejoin status to %s failed: %s", node.id, e)

    def _start_job(self, new_nodes: list[Node], removed: Optional[Node] = None) -> int:
        if not self.cluster.is_coordinator():
            raise ResizeError("resize must run on the coordinator")
        if self._new_nodes is not None:
            raise ResizeError("a resize job is already running")
        old_topo = self.cluster.topology
        new_topo = Topology(
            nodes=new_nodes,
            replica_n=old_topo.replica_n,
            partition_n=old_topo.partition_n,
            hasher=old_topo.hasher,
        )
        self._job_id += 1
        job = self._job_id
        self._active_job = job
        self._new_nodes = new_topo.nodes
        # Counted the moment the job is armed, not after start succeeds:
        # a start that fails mid-delivery runs abort() (counting
        # resize_jobs_aborted_total), and started >= completed + aborted
        # must hold for any jobs-in-flight dashboard expression.
        global_stats.count("resize_jobs_started_total")
        instructions = self._build_instructions(old_topo, new_topo, removed)
        # DOWN members cannot follow instructions or report completion —
        # waiting on them (or fail-fasting on their freeze delivery)
        # would wedge every post-failover join until the dead node
        # returns. They keep their membership; anti-entropy re-syncs
        # them when they come back.
        live_new = [n for n in new_topo.nodes if n.state != NODE_STATE_DOWN]
        self._pending_nodes = {n.id for n in live_new}
        # Final-status recipients: the union of old and new membership — a
        # removed node must still see the flip back to NORMAL.
        notify = {n.id: n for n in old_topo.nodes}
        notify.update({n.id: n for n in new_topo.nodes})
        self._notify_nodes = list(notify.values())

        # Anything failing past this point (state broadcast, instruction
        # delivery, local follow) must roll back rather than leave the
        # cluster frozen in RESIZING with a half-armed job.
        try:
            # Freeze writes cluster-wide while fragments move. The freeze
            # is a safety invariant for every node that SURVIVES into the
            # new topology (a survivor that keeps accepting writes while
            # its fragments copy would silently lose them at the flip), so
            # delivery to survivors is fail-fast; a node being removed is
            # best-effort — it is usually being removed precisely because
            # it is dead, and its post-freeze writes are lost by design
            # (the reference leaves removed-node data dirs behind too).
            self.cluster.set_state(STATE_RESIZING)
            freeze = Message.make(bc.MSG_CLUSTER_STATUS, state=STATE_RESIZING)
            live_ids = {n.id for n in live_new}
            for node in self._notify_nodes:
                if node.id == self.cluster.local_node.id:
                    continue
                try:
                    self.cluster.broadcaster.send_to(node, freeze)
                except Exception as e:
                    if node.id in live_ids:
                        raise ResizeError(
                            f"freeze broadcast to {node.id} failed: {e}"
                        ) from e
                    self.log.printf(
                        "resize: freeze to leaving/down node %s failed: %s",
                        node.id, e,
                    )
            schema = {"indexes": self.cluster.holder.schema()} if self.cluster.holder else {}
            available = self._available_map()
            for node in live_new:
                msg = Message.make(
                    bc.MSG_RESIZE_INSTRUCTION,
                    job=job,
                    node=node.id,
                    coordinator=self.cluster.local_node.to_json(),
                    sources=instructions.get(node.id, []),
                    schema=schema,
                    available=available,
                )
                if node.id == self.cluster.local_node.id:
                    self.follow_instruction(msg)
                else:
                    try:
                        self.cluster.broadcaster.send_to(node, msg)
                    except Exception as e:
                        # An unreachable node would wedge the job in
                        # RESIZING forever; roll back instead.
                        raise ResizeError(
                            f"instruction delivery to {node.id} failed: {e}"
                        ) from e
        except Exception as e:
            self.log.printf("resize: job %d failed to start: %s", job, e)
            self.abort()
            raise
        global_stats.gauge("resize_pending_nodes", len(self._pending_nodes))
        self._arm_timeout(job)
        return job

    def _broadcast_best_effort(self, msg: Message, nodes=None) -> None:
        """Deliver to the given nodes (default: current topology), logging
        failures instead of raising: a dead peer must not stop state
        transitions from reaching the survivors (code review r3:
        fail-fast send_sync left reachable nodes frozen in RESIZING)."""
        for node in (nodes if nodes is not None else self.cluster.topology.nodes):
            if node.id == self.cluster.local_node.id:
                continue
            try:
                self.cluster.broadcaster.send_to(node, msg)
            except Exception as e:
                self.log.printf("resize: broadcast to %s failed: %s", node.id, e)

    def _arm_timeout(self, job: int) -> None:
        t = threading.Timer(self.job_timeout, self._timeout_job, args=(job,))
        t.daemon = True
        with self._lock:
            self._timer = t
        t.start()

    def _timeout_job(self, job: int) -> None:
        with self._lock:
            if self._active_job != job or self._new_nodes is None:
                return  # completed or already aborted
            pending = sorted(self._pending_nodes)
        self.log.printf(
            "resize job %d timed out after %.0fs waiting on %s: aborting",
            job, self.job_timeout, pending,
        )
        # only_job guards the race where the final completion lands
        # between the check above and the abort: aborting a job that
        # already finished would re-freeze the NEW topology.
        self.abort(only_job=job)

    def _available_map(self) -> dict:
        """index -> field -> cluster-wide available shards (the joiner must
        fan queries out to every shard, not just the ones it fetched)."""
        holder = self.cluster.holder
        out: dict[str, dict[str, list[int]]] = {}
        if holder is None:
            return out
        for index_name in list(holder.indexes):
            idx = holder.index(index_name)
            if idx is None:
                continue
            for field_name in list(idx.fields):
                f = idx.field(field_name)
                if f is not None:
                    out.setdefault(index_name, {})[field_name] = [
                        int(s) for s in f.available_shards().to_array().tolist()
                    ]
        return out

    def _build_instructions(self, old_topo: Topology, new_topo: Topology,
                            removed: Optional[Node]) -> dict[str, list[dict]]:
        """node id -> fragment sources (reference fragSources cluster.go:784).
        A node fetches every (index, field, shard) it owns in the new
        topology but not the old, from any surviving old owner."""
        holder = self.cluster.holder
        out: dict[str, list[dict]] = {}
        if holder is None:
            return out
        gone_id = removed.id if removed is not None else None
        for index_name in list(holder.indexes):
            idx = holder.index(index_name)
            if idx is None:
                continue
            for field_name in list(idx.fields):
                f = idx.field(field_name)
                if f is None:
                    continue
                for shard in f.available_shards().to_array().tolist():
                    old_owners = [
                        n for n in old_topo.shard_nodes(index_name, shard)
                        if n.id != gone_id and n.state != NODE_STATE_DOWN
                    ]
                    if removed is not None:
                        # The leaving node's data must survive: it stays a
                        # valid source for fragments only it holds.
                        old_owners = old_owners + [removed]
                    old_ids = {n.id for n in old_topo.shard_nodes(index_name, shard)}
                    if not old_owners:
                        continue
                    for node in new_topo.shard_nodes(index_name, shard):
                        if node.id in old_ids:
                            continue  # already holds it
                        src = next(
                            (o for o in old_owners if o.id != node.id), old_owners[0]
                        )
                        out.setdefault(node.id, []).append(
                            {
                                "index": index_name,
                                "field": field_name,
                                "shard": int(shard),
                                "from": str(src.uri),
                            }
                        )
        return out

    # -- every node: instruction following (reference cluster.go:1297) -----

    def follow_instruction(self, msg: Message) -> None:
        """Fetch assigned fragments, then report completion. Runs inline —
        callers that need async wrap it in a thread (the HTTP receive path
        does, so the coordinator isn't blocked on its own broadcast).

        Completion is reported even when the fetch fails part-way (with an
        'error' field): a silent dead thread would wedge the whole cluster
        in RESIZING (ADVICE r2); incomplete data heals via anti-entropy.
        """
        err = None
        try:
            self._follow_instruction_inner(msg)
        except Exception as e:  # noqa: BLE001 — any failure must still report
            err = str(e)
            self.log.printf("resize: follow_instruction failed: %s", e)
        coord = Node.from_json(msg["coordinator"])
        done = Message.make(
            bc.MSG_RESIZE_COMPLETE,
            job=msg.get("job"),
            node=self.cluster.local_node.id,
            **({"error": err} if err else {}),
        )
        if coord.id == self.cluster.local_node.id:
            self.mark_complete(done)
        else:
            try:
                self.cluster.broadcaster.send_to(coord, done)
            except Exception as e:
                self.log.printf("resize: completion report failed: %s", e)

    def _follow_instruction_inner(self, msg: Message) -> None:
        # A joining node first needs the schema the cluster already has.
        if self.cluster.api is not None and msg.get("schema"):
            self.cluster.api.apply_schema(msg["schema"])
        from pilosa_tpu.cluster.sync import wrap_translate_stores

        wrap_translate_stores(self.cluster)
        holder = self.cluster.holder
        for index_name, fields in msg.get("available", {}).items():
            idx = holder.index(index_name) if holder else None
            if idx is None:
                continue
            for field_name, shards in fields.items():
                f = idx.field(field_name)
                if f is not None:
                    for s in shards:
                        f.add_available_shard(int(s))
        # Shard-migration progress gauges (ISSUE r8): a wedged resize is
        # a flatlined resize_migration_sources_done under a nonzero
        # _total, instead of silence. Totals are per-instruction (they
        # reset when the next job's instruction arrives).
        sources = msg.get("sources", [])
        global_stats.gauge("resize_migration_sources_total", len(sources))
        global_stats.gauge("resize_migration_sources_done", 0)
        for n_done, src in enumerate(sources):
            index, field_name = src["index"], src["field"]
            shard, from_uri = int(src["shard"]), src["from"]
            idx = holder.index(index) if holder else None
            f = idx.field(field_name) if idx else None
            if f is None:
                continue
            try:
                view_names = self.cluster.client.field_state(
                    from_uri, index, field_name
                ).get("views", [])
            except ClientError as e:
                self.log.printf("resize: view list from %s: %s", from_uri, e)
                view_names = []
            for view_name in view_names:
                try:
                    data = self.cluster.client.retrieve_shard(
                        from_uri, index, field_name, view_name, shard
                    )
                except ClientError:
                    continue  # fragment absent in this view
                f.import_roaring(shard, data, view_name=view_name)
            f.add_available_shard(shard)
            global_stats.count("resize_fragments_fetched_total")
            global_stats.gauge("resize_migration_sources_done", n_done + 1)
        # Unconditional final set: sources skipped at the tail (field not
        # held locally) must not leave _done below _total forever — that
        # is the wedged-resize signature and would be a standing false
        # alarm on a job that completed fine.
        global_stats.gauge("resize_migration_sources_done", len(sources))
        self._needs_clean = True

    # -- coordinator: completion tracking (reference cluster.go:1413) ------

    def mark_complete(self, msg: Message) -> None:
        with self._lock:
            if msg.get("job") != self._active_job:
                # Stale COMPLETE from an aborted/earlier job must not
                # satisfy a later job's pending set (ADVICE r2): flipping
                # topology before copies finish silently loses data.
                return
            if msg.get("error"):
                self.log.printf(
                    "resize: node %s completed with error: %s",
                    msg.get("node"), msg.get("error"),
                )
            self._pending_nodes.discard(msg.get("node"))
            global_stats.gauge("resize_pending_nodes", len(self._pending_nodes))
            if self._pending_nodes or self._new_nodes is None:
                return
            new_nodes = self._new_nodes
            notify = self._notify_nodes
            self._notify_nodes = []
            self._new_nodes = None
            self._active_job = None
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        # Counted at the decision point, BEFORE the status broadcast: an
        # observer that sees the cluster flip to NORMAL must already see
        # the completion on /metrics.
        global_stats.count("resize_jobs_completed_total")
        # Flip the whole cluster to the new topology atomically via one
        # status broadcast; receivers clean unowned fragments. Recipients
        # are old∪new members (send_sync would miss the joiner/leaver
        # because the coordinator's own topology flips only on receive).
        status = Message.make(
            bc.MSG_CLUSTER_STATUS,
            state=STATE_NORMAL,
            nodes=[n.to_json() for n in new_nodes],
            # A --join node boots with its own default; the cluster's
            # replication factor must override or its shard_nodes view
            # diverges from every other member.
            replicaN=self.cluster.topology.replica_n,
        )
        self.cluster.receive_message(status.to_bytes())
        for node in notify:
            if node.id != self.cluster.local_node.id:
                try:
                    self.cluster.broadcaster.send_to(node, status)
                except Exception as e:
                    self.log.printf("resize: status to %s failed: %s", node.id, e)
        self.log.printf("resize complete: %d nodes", len(new_nodes))

    def abort(self, only_job: Optional[int] = None) -> None:
        """Roll back to NORMAL on the old topology (reference api.go:1250).
        only_job: abort only if that job is still active (timeout path)."""
        with self._lock:
            if only_job is not None and self._active_job != only_job:
                return  # job completed/was replaced while we decided
            if self._active_job is not None:
                global_stats.count("resize_jobs_aborted_total")
            global_stats.gauge("resize_pending_nodes", 0)
            self._pending_nodes = set()
            self._new_nodes = None
            self._active_job = None
            self._needs_clean = False
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            # old∪new membership: a joiner that already received its
            # instruction must learn the job died, even though it is not
            # in topology.nodes yet (same reason mark_complete notifies
            # this set).
            notify = {n.id: n for n in self.cluster.topology.nodes}
            notify.update({n.id: n for n in self._notify_nodes})
            self._notify_nodes = []
        self.cluster.set_state(STATE_NORMAL)
        if self.cluster.is_coordinator():
            # Best-effort delivery: a dead peer (often the very reason for
            # the abort) must not stop survivors from unfreezing.
            targets = list(notify.values())
            self._broadcast_best_effort(Message.make(bc.MSG_RESIZE_ABORT), targets)
            self._broadcast_best_effort(
                Message.make(bc.MSG_CLUSTER_STATUS, state=STATE_NORMAL), targets
            )

    # -- every node: post-resize cleanup (reference holder.go:1104) --------

    def clean_holder(self) -> int:
        """Drop fragments for shards this node no longer owns. Runs after
        the topology flip to NORMAL; returns fragments removed."""
        with self._lock:
            if not self._needs_clean:
                return 0
            self._needs_clean = False
        holder = self.cluster.holder
        if holder is None:
            return 0
        removed = 0
        local_id = self.cluster.local_node.id
        # A node that is no longer a member keeps its data (the reference
        # leaves removed-node data dirs intact too).
        if self.cluster.topology.node_by_id(local_id) is None:
            return 0
        for index_name in list(holder.indexes):
            idx = holder.index(index_name)
            if idx is None:
                continue
            for field_name in list(idx.fields):
                f = idx.field(field_name)
                if f is None:
                    continue
                for view in list(f.views.values()):
                    for shard in list(view.fragments):
                        if not self.cluster.topology.owns_shard(
                            local_id, index_name, shard
                        ):
                            view.delete_fragment(shard)
                            removed += 1
        if removed:
            self.log.printf("holder cleaner: removed %d fragments", removed)
        return removed
