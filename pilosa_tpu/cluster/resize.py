"""Cluster resize: elastic add/remove of nodes with fragment re-placement
(reference cluster.go:784-868 fragSources, :1196-1441 resizeJob /
followResizeInstruction, holder.go:1104 holderCleaner).

Flow (coordinator-driven state machine, reference cluster.go:47-50):

1. Coordinator receives add/remove (HTTP endpoint or a JOIN node event),
   snapshots the old topology, builds the new one, and diffs placement:
   for every (index, shard) a node owns in the NEW topology but not the
   OLD, an instruction entry points it at a surviving old owner.
2. State broadcasts to RESIZING (API writes 503 during the move), then
   each node gets a MSG_RESIZE_INSTRUCTION and fetches whole fragments
   over /internal/fragment/data (reference RetrieveShardFromURI
   http/client.go:742), unioning them into local storage.
3. Nodes report MSG_RESIZE_COMPLETE; when all have, the coordinator
   broadcasts the new node list with state NORMAL; every node then drops
   fragments it no longer owns (holderCleaner).
4. Abort (POST /cluster/resize/abort, reference api.go:1250) rolls state
   back to NORMAL on the old topology.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.broadcast import Message
from pilosa_tpu.cluster.client import ClientError
from pilosa_tpu.cluster.topology import (
    NODE_STATE_DOWN,
    Node,
    STATE_NORMAL,
    STATE_RESIZING,
    Topology,
)
from pilosa_tpu.utils.logger import NopLogger
from pilosa_tpu.utils.stats import global_stats


class ResizeError(Exception):
    pass


class Resizer:
    """Owns resize jobs on the coordinator and instruction-following on
    every node. Installed via cluster.attach_resizer()."""

    #: Coordinator-side auto-abort: a job whose completions don't all
    #: arrive within this window rolls back instead of wedging the
    #: cluster in RESIZING (ADVICE r2: no manual-abort-only escape).
    job_timeout: float = 600.0
    #: Follower-side lease (ISSUE r9 tentpole 1): a node frozen in
    #: RESIZING that hears neither a coordinator heartbeat nor a terminal
    #: status for this long rolls itself back to NORMAL on the old
    #: topology. This is the escape hatch the coordinator's own timer
    #: cannot be — that timer dies with the coordinator process, and a
    #: dead coordinator used to strand every follower answering 503
    #: forever. Config knob: resize-lease.
    lease_timeout: float = 90.0
    #: Per-source retry budget for transient fragment-fetch failures
    #: (transport, checksum mismatch, 5xx) before failing over to the
    #: next surviving old owner.
    fetch_retries: int = 2
    #: Concurrent fragment fetches per instruction (config knob:
    #: migration-concurrency). Bounded so a resize's fan-in cannot
    #: starve the serving path's sockets and device time.
    fetch_concurrency: int = 2
    #: Aggregate migration bandwidth cap in bytes/s across all fetch
    #: workers (config knob: migration-bandwidth; 0 = uncapped).
    bandwidth_limit: int = 0
    #: Per-RPC budget for migration fetches: each fetch opens a Deadline
    #: scope (the PR 4 plane) so its socket timeout is bounded and the
    #: budget rides X-Pilosa-Deadline to the source node.
    fetch_timeout: float = 30.0

    def __init__(self, cluster, logger=None):
        self.cluster = cluster
        self.log = logger or NopLogger()
        self._lock = threading.RLock()
        self._job_id = 0
        # Job epoch (ISSUE r9 tentpole 1): instructions and completions
        # carry it, mark_complete requires it to match. A promoted
        # coordinator adopting a dead coordinator's in-flight job bumps
        # past the highest epoch it observed, so the dead job's stale
        # COMPLETEs can never satisfy a new job whose fresh counter
        # happens to reuse the same job id.
        self._epoch = 0
        # Highest epoch / last job this node observed as a follower
        # (instructions, heartbeats) — what a promotion adopts from.
        self._observed_epoch = 0
        self._observed_job: Optional[int] = None
        # Coordinator-side live job state.
        self._active_job: Optional[int] = None
        self._pending_nodes: set[str] = set()
        self._new_nodes: Optional[list[Node]] = None
        self._notify_nodes: list[Node] = []
        self._timer: Optional[threading.Timer] = None
        # Follower-side lease timer + coordinator-side heartbeat stop.
        self._lease: Optional[threading.Timer] = None
        self._hb_stop: Optional[threading.Event] = None
        # Migration-fetch cancellation: each follow_instruction run gets
        # a generation; a lease expiry or abort cancels the CURRENT
        # generation so in-flight fetch workers stop instead of
        # migrating (and re-arming cleanup) for a dead job.
        self._follow_gen = 0
        self._follow_cancel_gen = 0
        # Aggregate bandwidth pacing across concurrent fetch workers.
        self._bw_lock = threading.Lock()
        self._bw_next = 0.0
        # (index, shard) pairs an active instruction is currently
        # migrating onto this node (ISSUE r15 satellite): the
        # anti-entropy / read-repair planes skip these — a repair
        # sourced mid-move would treat a half-migrated fragment as
        # truth. Guarded by its own leaf lock: the hot consumer is the
        # sync loop, which must not contend on the resizer RLock the
        # coordinator's inline instruction-follow holds.
        self._migrating: set[tuple[str, int]] = set()
        self._migrating_lock = threading.Lock()
        # Set on every node while it should clean after the topology flips.
        self._needs_clean = False
        cluster.resizer = self

    # -- coordinator: job control (reference cluster.go:1196) --------------

    def add_node(self, node: Node) -> int:
        """Grow the cluster by one node; returns the job id."""
        with self._lock:
            if self.cluster.topology.node_by_id(node.id) is not None:
                raise ResizeError(f"node already in cluster: {node.id}")
            new_nodes = [
                Node(n.id, n.uri, n.is_coordinator, n.state)
                for n in self.cluster.topology.nodes
            ] + [Node(node.id, node.uri, False)]
            # lint: allow-lock-discipline(control plane: job mutations serialize across the announce RPCs by design; the data path never takes this lock)
            return self._start_job(new_nodes)

    def remove_node(self, node_id: str) -> int:
        with self._lock:
            gone = self.cluster.topology.node_by_id(node_id)
            if gone is None:
                raise ResizeError(f"node not in cluster: {node_id}")
            if gone.is_coordinator:
                raise ResizeError("cannot remove the coordinator")
            new_nodes = [
                Node(n.id, n.uri, n.is_coordinator, n.state)
                for n in self.cluster.topology.nodes
                if n.id != node_id
            ]
            # lint: allow-lock-discipline(control plane: job mutations serialize across the announce RPCs by design; the data path never takes this lock)
            return self._start_job(new_nodes, removed=gone)

    def handle_join(self, node: Node) -> None:
        """A JOIN node event on the coordinator triggers a grow job
        (reference listenForJoins cluster.go:1141)."""
        try:
            self.add_node(node)
        except ResizeError:
            # Two reasons land here. A resize job already running: do
            # nothing, the joiner keeps re-announcing. Already a member:
            # that is a RESTARTED --join node (ADVICE r3 medium) — it
            # boots single-node believing itself coordinator while the
            # cluster still routes shards to it, so re-send the current
            # schema + cluster status directly instead of silently
            # dropping the announce (reference nodeJoin re-sends
            # ClusterStatus to existing members, cluster.go:2121-2134).
            if self.cluster.topology.node_by_id(node.id) is None:
                return
            schema = (
                {"indexes": self.cluster.holder.schema()}
                if self.cluster.holder is not None
                else {}
            )
            status = Message.make(
                bc.MSG_CLUSTER_STATUS,
                state=self.cluster.state(),
                nodes=[n.to_json() for n in self.cluster.topology.nodes],
                replicaN=self.cluster.topology.replica_n,
            )
            try:
                self.cluster.broadcaster.send_to(
                    node,
                    Message.make(
                        bc.MSG_NODE_STATUS,
                        schema=schema,
                        # available shards too: the restarted node must
                        # fan queries out over every shard immediately,
                        # not after the next anti-entropy pass (the
                        # normal join path ships this in the resize
                        # instruction for the same reason).
                        available=self._available_map(),
                    ),
                )
                self.cluster.broadcaster.send_to(node, status)
            except Exception as e:  # noqa: BLE001 — joiner re-announces
                self.log.printf("resize: rejoin status to %s failed: %s", node.id, e)

    def _start_job(self, new_nodes: list[Node], removed: Optional[Node] = None) -> int:
        if not self.cluster.is_coordinator():
            raise ResizeError("resize must run on the coordinator")
        if self._new_nodes is not None:
            raise ResizeError("a resize job is already running")
        old_topo = self.cluster.topology
        new_topo = Topology(
            nodes=new_nodes,
            replica_n=old_topo.replica_n,
            partition_n=old_topo.partition_n,
            hasher=old_topo.hasher,
        )
        self._job_id += 1
        # Every job gets a FRESH epoch, so a dead job's straggler
        # COMPLETE (still retrying through its reporter's backoff) can
        # never carry this job's (job, epoch) identity even when the
        # job counter collides across aborts or coordinator changes.
        self._epoch += 1
        job = self._job_id
        self._active_job = job
        self._new_nodes = new_topo.nodes
        # Counted the moment the job is armed, not after start succeeds:
        # a start that fails mid-delivery runs abort() (counting
        # resize_jobs_aborted_total), and started >= completed + aborted
        # must hold for any jobs-in-flight dashboard expression.
        global_stats.count("resize_jobs_started_total")
        instructions = self._build_instructions(old_topo, new_topo, removed)
        # DOWN members cannot follow instructions or report completion —
        # waiting on them (or fail-fasting on their freeze delivery)
        # would wedge every post-failover join until the dead node
        # returns. They keep their membership; anti-entropy re-syncs
        # them when they come back.
        live_new = [n for n in new_topo.nodes if n.state != NODE_STATE_DOWN]
        self._pending_nodes = {n.id for n in live_new}
        # Final-status recipients: the union of old and new membership — a
        # removed node must still see the flip back to NORMAL.
        notify = {n.id: n for n in old_topo.nodes}
        notify.update({n.id: n for n in new_topo.nodes})
        self._notify_nodes = list(notify.values())

        # Anything failing past this point (state broadcast, instruction
        # delivery, local follow) must roll back rather than leave the
        # cluster frozen in RESIZING with a half-armed job.
        try:
            # Freeze writes cluster-wide while fragments move. The freeze
            # is a safety invariant for every node that SURVIVES into the
            # new topology (a survivor that keeps accepting writes while
            # its fragments copy would silently lose them at the flip), so
            # delivery to survivors is fail-fast; a node being removed is
            # best-effort — it is usually being removed precisely because
            # it is dead, and its post-freeze writes are lost by design
            # (the reference leaves removed-node data dirs behind too).
            self.cluster.set_state(STATE_RESIZING)
            freeze = Message.make(bc.MSG_CLUSTER_STATUS, state=STATE_RESIZING)
            live_ids = {n.id for n in live_new}
            for node in self._notify_nodes:
                if node.id == self.cluster.local_node.id:
                    continue
                try:
                    self.cluster.broadcaster.send_to(node, freeze)
                except Exception as e:
                    if node.id in live_ids:
                        raise ResizeError(
                            f"freeze broadcast to {node.id} failed: {e}"
                        ) from e
                    self.log.printf(
                        "resize: freeze to leaving/down node %s failed: %s",
                        node.id, e,
                    )
            schema = {"indexes": self.cluster.holder.schema()} if self.cluster.holder else {}
            available = self._available_map()
            for node in live_new:
                msg = Message.make(
                    bc.MSG_RESIZE_INSTRUCTION,
                    job=job,
                    epoch=self._epoch,
                    node=node.id,
                    coordinator=self.cluster.local_node.to_json(),
                    sources=instructions.get(node.id, []),
                    schema=schema,
                    available=available,
                )
                if node.id == self.cluster.local_node.id:
                    self.follow_instruction(msg)
                else:
                    try:
                        self.cluster.broadcaster.send_to(node, msg)
                    except Exception as e:
                        # An unreachable node would wedge the job in
                        # RESIZING forever; roll back instead.
                        raise ResizeError(
                            f"instruction delivery to {node.id} failed: {e}"
                        ) from e
        except Exception as e:
            self.log.printf("resize: job %d failed to start: %s", job, e)
            self.abort()
            raise
        global_stats.gauge("resize_pending_nodes", len(self._pending_nodes))
        # The bumped epoch rides the topology file so a coordinator
        # RESTART cannot mint a fresh job with a dead job's identity.
        self.cluster.persist_topology()
        self._arm_timeout(job)
        self._start_heartbeats(job)
        return job

    def _broadcast_best_effort(self, msg: Message, nodes=None) -> None:
        """Deliver to the given nodes (default: current topology), logging
        failures instead of raising: a dead peer must not stop state
        transitions from reaching the survivors (code review r3:
        fail-fast send_sync left reachable nodes frozen in RESIZING)."""
        for node in (nodes if nodes is not None else self.cluster.topology.nodes):
            if node.id == self.cluster.local_node.id:
                continue
            try:
                self.cluster.broadcaster.send_to(node, msg)
            except Exception as e:
                self.log.printf("resize: broadcast to %s failed: %s", node.id, e)

    def _arm_timeout(self, job: int) -> None:
        t = threading.Timer(self.job_timeout, self._timeout_job, args=(job,))
        t.daemon = True
        with self._lock:
            self._timer = t
        t.start()

    def _timeout_job(self, job: int) -> None:
        with self._lock:
            if self._active_job != job or self._new_nodes is None:
                return  # completed or already aborted
            pending = sorted(self._pending_nodes)
        self.log.printf(
            "resize job %d timed out after %.0fs waiting on %s: aborting",
            job, self.job_timeout, pending,
        )
        # only_job guards the race where the final completion lands
        # between the check above and the abort: aborting a job that
        # already finished would re-freeze the NEW topology.
        self.abort(only_job=job)

    # -- coordinator: liveness heartbeats (ISSUE r9 tentpole 1) ------------

    def _start_heartbeats(self, job: int) -> None:
        """While a job is in flight the coordinator heartbeats every
        participant; followers renew their rollback lease on each one.
        When the coordinator process dies the heartbeats stop with it and
        every follower's lease expires — the failover path that used to
        not exist."""
        stop = threading.Event()
        with self._lock:
            if self._hb_stop is not None:
                self._hb_stop.set()
            self._hb_stop = stop
        from pilosa_tpu.utils.threads import spawn

        spawn("resize-lease", self._heartbeat_loop, args=(job, stop))

    def _heartbeat_loop(self, job: int, stop: threading.Event) -> None:
        # 3 heartbeats per lease window: one lost datagram-equivalent
        # cannot expire a healthy job's lease.
        interval = max(self.lease_timeout / 3.0, 0.05)
        while not stop.wait(interval):
            with self._lock:
                if self._active_job != job:
                    return
                targets = list(self._notify_nodes)
                msg = Message.make(
                    bc.MSG_RESIZE_HEARTBEAT, job=job, epoch=self._epoch
                )
            self._broadcast_best_effort(msg, targets)

    def _stop_heartbeats(self) -> None:
        with self._lock:
            stop, self._hb_stop = self._hb_stop, None
        if stop is not None:
            stop.set()

    # -- every node: rollback lease (ISSUE r9 tentpole 1) ------------------

    def renew_lease(self, msg: Optional[Message] = None) -> None:
        """(Re)arm the follower-side rollback lease. Called when this
        node observes the cluster freeze (MSG_CLUSTER_STATUS RESIZING),
        receives a resize instruction, or receives a coordinator
        heartbeat. The coordinator's own job is excluded — its
        job_timeout owns termination there."""
        if msg is not None:
            with self._lock:
                self._observed_epoch = max(
                    self._observed_epoch, int(msg.get("epoch") or 0)
                )
                if msg.get("job") is not None:
                    self._observed_job = msg.get("job")
        with self._lock:
            if self._new_nodes is not None:
                return  # our own job: the coordinator timer covers it
            if self._lease is not None:
                self._lease.cancel()
            t = threading.Timer(self.lease_timeout, self._lease_expired)
            t.daemon = True
            self._lease = t
        t.start()

    def cancel_lease(self) -> None:
        with self._lock:
            if self._lease is not None:
                self._lease.cancel()
                self._lease = None

    def _lease_expired(self) -> None:
        """No coordinator heartbeat or terminal status inside the lease
        window: the coordinator (or its job) is gone. Roll THIS node back
        to NORMAL on the old topology — the topology only flips on the
        completion broadcast, so state is all that needs reverting — and
        drop any pending cleanup (we may still own fragments the dead job
        meant to move)."""
        with self._lock:
            self._lease = None
        if self.cluster.state() != STATE_RESIZING:
            # Terminal status raced the timer: nothing to do. Checked
            # BEFORE touching _needs_clean — the completed job's
            # clean_holder() still needs that flag.
            return
        with self._lock:
            self._needs_clean = False
            # Stop any in-flight migration workers: fetching (and
            # re-arming cleanup) for a dead job wastes the links and
            # imports shards the rolled-back topology may not own.
            self._follow_cancel_gen = self._follow_gen
        global_stats.count("resize_lease_expirations_total")
        self.log.printf(
            "resize: lease expired after %.0fs without coordinator "
            "heartbeat; rolling back to NORMAL on the old topology",
            self.lease_timeout,
        )
        self.cluster.set_state(STATE_NORMAL)

    def follower_status(self) -> Optional[dict]:
        """This node's view of an in-flight resize it is FOLLOWING —
        surfaced in /status so a promoted coordinator that never saw the
        job (the old coordinator died before freezing it) learns about
        it from its liveness probes and can abort it for the stranded
        followers."""
        state = self.cluster.state()
        with self._lock:
            if state != STATE_RESIZING or self._new_nodes is not None:
                return None
            return {"job": self._observed_job, "epoch": self._observed_epoch}

    def on_promoted(self) -> None:
        """The local node just became coordinator. Any resize job the
        dead coordinator left in flight is adopted — and adoption means
        owning its TERMINATION: the pending-completion set died with the
        old coordinator, so blindly completing could flip topology before
        fragment copies finished (silent data loss). Roll the cluster
        back to the old topology under a bumped epoch instead; stale
        COMPLETEs from the dead job are rejected by the epoch check, the
        operator re-issues the resize, and anti-entropy heals any
        partially-copied fragments."""
        state = self.cluster.state()
        with self._lock:
            observed = max(self._epoch, self._observed_epoch)
            if self._new_nodes is not None:
                return  # we own a live job already: nothing to adopt
            # Epoch advances PAST everything observed even when there is
            # nothing to abort: the dead coordinator's last job may still
            # have completion reports in retry flight, and our future
            # jobs must outrank it, never tie it.
            self._epoch = observed + 1
            if state != STATE_RESIZING:
                self.cluster.persist_topology()
                return
            job = self._observed_job
            epoch = self._epoch  # captured under the lock for the log
        self.cluster.persist_topology()
        global_stats.count("resize_jobs_adopted_total")
        self.log.printf(
            "resize: promoted mid-job; adopting orphaned job %s "
            "(new epoch %d) and aborting it", job, epoch,
        )
        self.abort()

    def observe_follower(self, info: dict) -> None:
        """Probe-reported resize state from a peer frozen in RESIZING on
        a job this coordinator doesn't own (we were promoted after the
        freeze reached them but before any instruction reached us):
        adopt-and-abort it so the stranded follower unfreezes before its
        own lease has to fire."""
        if not self.cluster.is_coordinator():
            return
        with self._lock:
            if self._new_nodes is not None:
                return  # our live job: heartbeats already cover the peer
            self._epoch = max(self._epoch, int(info.get("epoch") or 0) + 1)
            epoch = self._epoch  # captured under the lock for the log
        self.cluster.persist_topology()
        global_stats.count("resize_jobs_adopted_total")
        self.log.printf(
            "resize: follower reports orphaned job %s; aborting it "
            "(epoch now %d)", info.get("job"), epoch,
        )
        self.abort()

    def _available_map(self) -> dict:
        """index -> field -> cluster-wide available shards (the joiner must
        fan queries out to every shard, not just the ones it fetched)."""
        holder = self.cluster.holder
        out: dict[str, dict[str, list[int]]] = {}
        if holder is None:
            return out
        for index_name in list(holder.indexes):
            idx = holder.index(index_name)
            if idx is None:
                continue
            for field_name in list(idx.fields):
                f = idx.field(field_name)
                if f is not None:
                    out.setdefault(index_name, {})[field_name] = [
                        int(s) for s in f.available_shards().to_array().tolist()
                    ]
        return out

    def _build_instructions(self, old_topo: Topology, new_topo: Topology,
                            removed: Optional[Node]) -> dict[str, list[dict]]:
        """node id -> fragment sources (reference fragSources cluster.go:784).
        A node fetches every (index, field, shard) it owns in the new
        topology but not the old, from any surviving old owner."""
        holder = self.cluster.holder
        out: dict[str, list[dict]] = {}
        if holder is None:
            return out
        gone_id = removed.id if removed is not None else None
        for index_name in list(holder.indexes):
            idx = holder.index(index_name)
            if idx is None:
                continue
            for field_name in list(idx.fields):
                f = idx.field(field_name)
                if f is None:
                    continue
                for shard in f.available_shards().to_array().tolist():
                    old_owners = [
                        n for n in old_topo.shard_nodes(index_name, shard)
                        if n.id != gone_id and n.state != NODE_STATE_DOWN
                    ]
                    if removed is not None:
                        # The leaving node's data must survive: it stays a
                        # valid source for fragments only it holds.
                        old_owners = old_owners + [removed]
                    old_ids = {n.id for n in old_topo.shard_nodes(index_name, shard)}
                    if not old_owners:
                        continue
                    for node in new_topo.shard_nodes(index_name, shard):
                        if node.id in old_ids:
                            continue  # already holds it
                        src = next(
                            (o for o in old_owners if o.id != node.id), old_owners[0]
                        )
                        # Every OTHER surviving old owner rides along as
                        # an alternate: the fetcher fails over to them
                        # when the primary source flakes or serves a
                        # corrupt payload (ISSUE r9 tentpole 2).
                        alts = [
                            str(o.uri)
                            for o in old_owners
                            if o.id not in (node.id, src.id)
                        ]
                        out.setdefault(node.id, []).append(
                            {
                                "index": index_name,
                                "field": field_name,
                                "shard": int(shard),
                                "from": str(src.uri),
                                "alts": alts,
                            }
                        )
        return out

    # -- every node: instruction following (reference cluster.go:1297) -----

    def follow_instruction(self, msg: Message) -> None:
        """Fetch assigned fragments, then report completion. Runs inline —
        callers that need async wrap it in a thread (the HTTP receive path
        does, so the coordinator isn't blocked on its own broadcast).

        Completion is reported even when the fetch fails part-way (with an
        'error' field): a silent dead thread would wedge the whole cluster
        in RESIZING (ADVICE r2); incomplete data heals via anti-entropy.
        """
        self.renew_lease(msg)
        err = None
        try:
            self._follow_instruction_inner(msg)
        except Exception as e:  # noqa: BLE001 — any failure must still report
            err = str(e)
            self.log.printf("resize: follow_instruction failed: %s", e)
        done = Message.make(
            bc.MSG_RESIZE_COMPLETE,
            job=msg.get("job"),
            epoch=int(msg.get("epoch") or 0),
            node=self.cluster.local_node.id,
            **({"error": err} if err else {}),
        )
        self._report_complete(done, msg)

    def _report_complete(self, done: Message, instruction: Message) -> None:
        """Deliver the completion report with capped jittered backoff
        against the CURRENTLY resolved coordinator, re-resolving each
        attempt (ISSUE r9 tentpole 1): the old single-shot send was
        logged and dropped, so a coordinator crash between instruction
        and completion wedged the job even after a successor was
        promoted. Retries stop when the report lands, the cluster left
        RESIZING (abort/lease rollback owns recovery), or the lease
        window is spent (the lease rollback takes over)."""
        fallback = Node.from_json(instruction["coordinator"])
        backoff, cap = 0.25, 5.0
        give_up = time.monotonic() + self.lease_timeout
        attempt = 0
        while True:
            attempt += 1
            # Only an explicitly FLAGGED coordinator counts as resolved:
            # a joiner's topology is just itself until the flip, and the
            # positional coordinator() fallback would resolve the joiner
            # itself, silently self-delivering the report into the void.
            coord = next(
                (n for n in self.cluster.topology.nodes if n.is_coordinator),
                None,
            ) or fallback
            if coord.id == self.cluster.local_node.id:
                self.mark_complete(done)
                return
            try:
                self.cluster.broadcaster.send_to(coord, done)
                return
            except Exception as e:  # noqa: BLE001 — retried below
                global_stats.count("resize_complete_retries_total")
                self.log.printf(
                    "resize: completion report to %s failed "
                    "(attempt %d): %s", coord.id, attempt, e,
                )
            if (
                time.monotonic() >= give_up
                or self.cluster.state() != STATE_RESIZING
            ):
                self.log.printf(
                    "resize: giving up on completion report after %d "
                    "attempts; lease rollback owns recovery", attempt,
                )
                return
            time.sleep(min(backoff, cap) * (0.5 + random.random()))
            backoff = min(backoff * 2, cap)

    def _follow_instruction_inner(self, msg: Message) -> None:
        # A joining node first needs the schema the cluster already has.
        if self.cluster.api is not None and msg.get("schema"):
            self.cluster.api.apply_schema(msg["schema"])
        from pilosa_tpu.cluster.sync import wrap_translate_stores

        wrap_translate_stores(self.cluster)
        holder = self.cluster.holder
        for index_name, fields in msg.get("available", {}).items():
            idx = holder.index(index_name) if holder else None
            if idx is None:
                continue
            for field_name, shards in fields.items():
                f = idx.field(field_name)
                if f is not None:
                    for s in shards:
                        f.add_available_shard(int(s))
        # Shard-migration progress gauges (ISSUE r8): a wedged resize is
        # a flatlined resize_migration_sources_done under a nonzero
        # _total, instead of silence. Totals are per-instruction (they
        # reset when the next job's instruction arrives).
        sources = msg.get("sources", [])
        global_stats.gauge("resize_migration_sources_total", len(sources))
        global_stats.gauge("resize_migration_sources_done", 0)
        # Window the whole instruction's shard set as migration-in-flight
        # (not per-source): a queued-but-unfetched source is about to be
        # overwritten, so repairing it mid-window is wasted work at best
        # and a half-block ship at worst (ISSUE r15 satellite).
        inflight_keys = {
            (str(s.get("index")), int(s.get("shard", 0))) for s in sources
        }
        with self._migrating_lock:
            self._migrating |= inflight_keys
        # Bounded fan-out (ISSUE r9 tentpole 2): fetch_concurrency
        # workers pull sources off a shared queue; failures are
        # aggregated and reported in the completion's error field (the
        # topology still flips — incomplete data heals via anti-entropy)
        # instead of silently skipped.
        workers = max(int(self.fetch_concurrency), 1)
        state_lock = threading.Lock()
        n_done = [0]
        errors: list[str] = []
        queue = list(sources)
        with self._lock:
            self._follow_gen += 1
            gen = self._follow_gen

        def cancelled() -> bool:
            # Deliberately lockless: the coordinator's own instruction
            # runs INLINE under self._lock (add_node → _start_job →
            # follow_instruction), so workers taking the lock here would
            # deadlock against the joining owner. Single int read is
            # atomic; a one-iteration-late cancel observation is fine.
            return self._follow_cancel_gen >= gen

        def worker() -> None:
            while True:
                if cancelled():
                    return  # lease expired / job aborted: stop migrating
                with state_lock:
                    if not queue:
                        return
                    src = queue.pop(0)
                try:
                    self._fetch_source(holder, src, cancelled)
                except Exception as e:  # noqa: BLE001 — aggregated below
                    self.log.printf(
                        "resize: source %s/%s/%s failed: %s",
                        src.get("index"), src.get("field"),
                        src.get("shard"), e,
                    )
                    with state_lock:
                        errors.append(
                            f"{src.get('index')}/{src.get('field')}/"
                            f"{src.get('shard')}: {e}"
                        )
                finally:
                    with state_lock:
                        n_done[0] += 1
                        global_stats.gauge(
                            "resize_migration_sources_done", n_done[0]
                        )

        from pilosa_tpu.utils.threads import spawn

        threads = [
            spawn("resize-worker", worker, start=False)
            for _ in range(min(workers, max(len(sources), 1)))
        ]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            # The move window is over (success, cancel, or error): the
            # repair planes may touch these shards again. Set-difference,
            # not clear: an overlapping instruction for OTHER shards
            # keeps its registrations (two windows sharing a shard — a
            # failover re-delivery — just degrade that shard to the
            # pre-skip behavior one pass early, which is safe).
            with self._migrating_lock:
                self._migrating -= inflight_keys
        # Unconditional final set: sources skipped at the tail (field not
        # held locally) must not leave _done below _total forever — that
        # is the wedged-resize signature and would be a standing false
        # alarm on a job that completed fine.
        global_stats.gauge("resize_migration_sources_done", len(sources))
        if cancelled():
            # The lease rollback (or abort) already decided this job is
            # dead: _needs_clean must stay dropped — re-arming it would
            # let the NEXT terminal status trigger cleanup off a dead
            # job's state.
            raise ResizeError(
                "migration cancelled (lease expired or job aborted)"
            )
        self._needs_clean = True
        if errors:
            raise ResizeError(
                f"{len(errors)} of {len(sources)} fragment sources "
                "failed: " + "; ".join(errors[:3])
            )

    def migration_in_flight(self, index: str, shard: int) -> bool:
        """True while an active instruction is migrating this shard onto
        this node — the anti-entropy/read-repair skip predicate
        (anti_entropy_skipped_total{reason=resizing})."""
        with self._migrating_lock:
            return (index, int(shard)) in self._migrating

    # -- migration fetch plane (ISSUE r9 tentpole 2) -----------------------

    def _fetch_source(self, holder, src: dict, cancelled=None) -> None:
        """One instruction source: every view of one (index, field,
        shard), verified and failover-capable. The primary source plus
        every other surviving old owner ('alts') are candidates.
        cancelled (optional callable) is checked between views so a
        lease expiry or abort stops a long throttled fetch mid-source."""
        from pilosa_tpu.utils.deadline import Deadline, deadline_scope

        index, field_name = src["index"], src["field"]
        shard = int(src["shard"])
        candidates = [src["from"]] + [
            u for u in src.get("alts", []) if u != src["from"]
        ]
        idx = holder.index(index) if holder else None
        f = idx.field(field_name) if idx else None
        if f is None:
            return
        view_names = None
        last_err: Optional[Exception] = None
        for uri in candidates:
            try:
                with deadline_scope(Deadline(self.fetch_timeout)):
                    view_names = self.cluster.client.field_state(
                        uri, index, field_name
                    ).get("views", [])
                break
            except ClientError as e:
                last_err = e
                self._count_fetch_error(e)
        if view_names is None:
            raise ResizeError(
                f"no reachable source for view list: {last_err}"
            )
        for view_name in view_names:
            if cancelled is not None and cancelled():
                raise ResizeError("migration cancelled mid-source")
            data = self._fetch_fragment(
                candidates, index, field_name, view_name, shard
            )
            if data is None:
                continue  # absent on every surviving source
            # epoch_unknown: this is a COPY of another replica's data,
            # not a new write — minting fresh block epochs here would
            # out-date genuinely newer blocks on surviving replicas and
            # let directed repair wipe them with this (possibly stale)
            # migrated snapshot.
            f.import_roaring(
                shard, data, view_name=view_name, epoch_unknown=True
            )
            self._throttle(len(data))
        f.add_available_shard(shard)
        global_stats.count("resize_fragments_fetched_total")

    def _fetch_fragment(self, candidates, index: str, field: str,
                        view: str, shard: int) -> Optional[bytes]:
        """One verified fragment payload from the first source able to
        serve it. A 404 is a peer DECISION — 'fragment absent in this
        view' — and moves to the next source without burning retries
        (the old `except ClientError: continue` conflated it with
        transport failure, silently skipping fragments a flaky link
        owed us). Transient failures (transport, checksum mismatch,
        5xx) get bounded per-source retries with jittered backoff, then
        fail over to the next surviving old owner. Checksum
        verification happens in the client (retrieve_shard): a corrupt
        transfer raises before import_roaring can ever ingest it."""
        from pilosa_tpu.utils.deadline import Deadline, deadline_scope

        last_err: Optional[Exception] = None
        for uri in candidates:
            delay = 0.05
            for attempt in range(max(self.fetch_retries, 0) + 1):
                try:
                    with deadline_scope(Deadline(self.fetch_timeout)):
                        return self.cluster.client.retrieve_shard(
                            uri, index, field, view, shard
                        )
                except ClientError as e:
                    if e.status == 404:
                        break  # absent at this source: not a failure
                    last_err = e
                    self._count_fetch_error(e)
                    if attempt < self.fetch_retries:
                        time.sleep(delay * (0.5 + random.random()))
                        delay = min(delay * 2, 1.0)
        if last_err is not None:
            raise ResizeError(
                f"fragment {index}/{field}/{view}/{shard} unfetchable "
                f"from any surviving source: {last_err}"
            )
        return None  # 404 everywhere: genuinely absent in this view

    @staticmethod
    def _count_fetch_error(e: Exception) -> None:
        if getattr(e, "code", "") == "checksum-mismatch":
            kind = "checksum"
        elif getattr(e, "transport", False):
            kind = "transport"
        else:
            kind = "http"
        global_stats.with_tags(f"kind:{kind}").count(
            "resize_fetch_errors_total"
        )

    def _throttle(self, nbytes: int) -> None:
        """Aggregate bandwidth pacing: each completed transfer reserves
        nbytes/limit seconds on a shared monotonic schedule, so the
        sustained fetch rate across ALL workers stays under
        bandwidth_limit bytes/s and a resize cannot saturate the links
        the serving path shares."""
        if self.bandwidth_limit <= 0 or nbytes <= 0:
            return
        cost = nbytes / float(self.bandwidth_limit)
        with self._bw_lock:
            now = time.monotonic()
            self._bw_next = max(self._bw_next, now) + cost
            wait = self._bw_next - now
        if wait > 0:
            time.sleep(wait)

    # -- coordinator: completion tracking (reference cluster.go:1413) ------

    def mark_complete(self, msg: Message) -> None:
        with self._lock:
            msg_epoch = int(msg.get("epoch") or 0)
            if msg.get("job") != self._active_job or (
                msg_epoch and msg_epoch != self._epoch
            ):
                # Stale COMPLETE from an aborted/earlier job — or from a
                # dead coordinator's epoch after a failover — must not
                # satisfy a later job's pending set (ADVICE r2): flipping
                # topology before copies finish silently loses data.
                # Epoch 0 means an epoch-UNAWARE legacy follower (every
                # live job stamps >= 1): accepted on job-id match so a
                # mixed-version rolling upgrade can still resize —
                # epoch-aware peers' stale reports stay rejected.
                return
            if msg.get("error"):
                self.log.printf(
                    "resize: node %s completed with error: %s",
                    msg.get("node"), msg.get("error"),
                )
            self._pending_nodes.discard(msg.get("node"))
            global_stats.gauge("resize_pending_nodes", len(self._pending_nodes))
            if self._pending_nodes or self._new_nodes is None:
                return
            new_nodes = self._new_nodes
            notify = self._notify_nodes
            self._notify_nodes = []
            self._new_nodes = None
            self._active_job = None
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        self._stop_heartbeats()
        # Counted at the decision point, BEFORE the status broadcast: an
        # observer that sees the cluster flip to NORMAL must already see
        # the completion on /metrics.
        global_stats.count("resize_jobs_completed_total")
        # Flip the whole cluster to the new topology atomically via one
        # status broadcast; receivers clean unowned fragments. Recipients
        # are old∪new members (send_sync would miss the joiner/leaver
        # because the coordinator's own topology flips only on receive).
        status = Message.make(
            bc.MSG_CLUSTER_STATUS,
            state=STATE_NORMAL,
            nodes=[n.to_json() for n in new_nodes],
            # A --join node boots with its own default; the cluster's
            # replication factor must override or its shard_nodes view
            # diverges from every other member.
            replicaN=self.cluster.topology.replica_n,
        )
        self.cluster.receive_message(status.to_bytes())
        for node in notify:
            if node.id != self.cluster.local_node.id:
                try:
                    self.cluster.broadcaster.send_to(node, status)
                except Exception as e:
                    self.log.printf("resize: status to %s failed: %s", node.id, e)
        self.log.printf("resize complete: %d nodes", len(new_nodes))

    def abort(self, only_job: Optional[int] = None,
              local: bool = False) -> None:
        """Roll back to NORMAL on the old topology (reference api.go:1250).
        only_job: abort only if that job is still active (timeout path).
        local: apply without re-broadcasting — the MSG_RESIZE_ABORT
        receive path uses this, because during a failover window two
        nodes can both hold the coordinator flag and a re-broadcast on
        receive ping-pongs the abort between them forever."""
        with self._lock:
            if only_job is not None and self._active_job != only_job:
                return  # job completed/was replaced while we decided
            if self._active_job is not None:
                global_stats.count("resize_jobs_aborted_total")
            global_stats.gauge("resize_pending_nodes", 0)
            self._pending_nodes = set()
            self._new_nodes = None
            self._active_job = None
            self._needs_clean = False
            # Any in-flight migration workers are fetching for the job
            # being aborted: stop them (see _lease_expired).
            # lint: allow-shared-state(deliberately lockless cancel flag: workers poll it WITHOUT the resizer lock because the coordinator's inline follow runs under it and joining on it deadlocked, see PR 9)
            self._follow_cancel_gen = self._follow_gen
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            # old∪new membership: a joiner that already received its
            # instruction must learn the job died, even though it is not
            # in topology.nodes yet (same reason mark_complete notifies
            # this set).
            notify = {n.id: n for n in self.cluster.topology.nodes}
            notify.update({n.id: n for n in self._notify_nodes})
            self._notify_nodes = []
        self._stop_heartbeats()
        self.cancel_lease()
        self.cluster.set_state(STATE_NORMAL)
        if not local and self.cluster.is_coordinator():
            # Best-effort delivery: a dead peer (often the very reason for
            # the abort) must not stop survivors from unfreezing.
            targets = list(notify.values())
            self._broadcast_best_effort(Message.make(bc.MSG_RESIZE_ABORT), targets)
            self._broadcast_best_effort(
                Message.make(bc.MSG_CLUSTER_STATUS, state=STATE_NORMAL), targets
            )

    # -- every node: post-resize cleanup (reference holder.go:1104) --------

    def clean_holder(self) -> int:
        """Drop fragments for shards this node no longer owns. Runs after
        the topology flip to NORMAL; returns fragments removed."""
        with self._lock:
            if not self._needs_clean:
                return 0
            self._needs_clean = False
        holder = self.cluster.holder
        if holder is None:
            return 0
        removed = 0
        local_id = self.cluster.local_node.id
        # A node that is no longer a member keeps its data (the reference
        # leaves removed-node data dirs intact too).
        if self.cluster.topology.node_by_id(local_id) is None:
            return 0
        for index_name in list(holder.indexes):
            idx = holder.index(index_name)
            if idx is None:
                continue
            for field_name in list(idx.fields):
                f = idx.field(field_name)
                if f is None:
                    continue
                for view in list(f.views.values()):
                    for shard in list(view.fragments):
                        if not self.cluster.topology.owns_shard(
                            local_id, index_name, shard
                        ):
                            view.delete_fragment(shard)
                            removed += 1
        if removed:
            self.log.printf("holder cleaner: removed %d fragments", removed)
        return removed
