"""Internal peer-to-peer HTTP client (reference http/client.go InternalClient).

The DCN data plane: query fan-out (QueryNode with shards pinned +
remote=true, reference http/client.go:268), imports, fragment block sync
for anti-entropy, whole-fragment retrieval for resize, control-plane
message delivery, and key-translation RPCs. stdlib urllib with persistent
behavior left to the OS; every call raises ClientError on transport or
HTTP-status failure so the scatter-gather layer can retry replicas.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence, Union

from pilosa_tpu.cluster.breaker import BreakerRegistry
from pilosa_tpu.cluster.topology import URI, Node
from pilosa_tpu.utils.deadline import current_deadline
from pilosa_tpu.utils.stats import global_stats
from pilosa_tpu.utils.tracing import global_tracer


class ClientError(Exception):
    def __init__(self, msg: str, status: int = 0, code: str = "",
                 transport: bool = False):
        super().__init__(msg)
        self.status = status
        # Machine-readable error class from the peer's JSON error body
        # (e.g. "not-found"); empty when the body carried none.
        self.code = code
        # True for dial/reset/timeout failures (no HTTP exchange
        # completed): the class the breaker counts and the only class an
        # idempotent-GET retry may act on — an HTTP error status is a
        # peer DECISION and retrying it re-asks a question already
        # answered.
        self.transport = transport


#: A transport timeout whose socket budget was at least this long counts
#: as breaker evidence even when the query deadline set (truncated) the
#: socket timeout: half a second of silence is the peer's fault, not the
#: budget's. Below it, a deadline-squeezed timeout is the query's own.
_FAIR_WINDOW = 0.5


def _uri_str(uri: Union[URI, Node, str]) -> str:
    if isinstance(uri, Node):
        uri = uri.uri
    return str(uri)


def peer_label(uri: Union[URI, Node, str]) -> str:
    """host:port tag value for per-peer RPC series. Node ids would read
    better but the client routinely dials bare URIs (resize sources,
    rejoin announces) where no id exists; host:port is the one identity
    every call site has."""
    u = _uri_str(uri)
    _, _, hostport = u.partition("://")
    return hostport or u


# Per-peer in-flight request counts behind the peer_rpc_inflight gauge:
# the client is shared across serving threads, so the counter lives at
# module scope under one lock and each _do publishes the new value.
_inflight_lock = threading.Lock()
_inflight: dict[str, int] = {}


def _track_inflight(peer: str, delta: int) -> None:
    with _inflight_lock:
        n = _inflight.get(peer, 0) + delta
        _inflight[peer] = n
        # Published INSIDE the lock: otherwise two racing updates can
        # publish in inverted order and pin the gauge at a stale nonzero
        # value — the exact stuck-peer signature operators alert on.
        global_stats.with_tags(f"peer:{peer}").gauge("peer_rpc_inflight", n)


def count_rpc_retry(peer: str, method: str) -> None:
    """One retargeted/re-sent peer RPC (scatter-gather re-split onto a
    replica, schema-repair re-query, wire renegotiation). The client
    itself never retries — the layers above do — so they report here to
    keep every peer_rpc_* series in one vocabulary."""
    global_stats.with_tags(f"peer:{peer}", f"method:{method}").count(
        "peer_rpc_retries_total"
    )


def _ts_epoch(t) -> int:
    """Timestamp (int seconds / PQL string / datetime / falsy) -> unix
    seconds for the wire (reference ImportRequest.Timestamps int64)."""
    if not t:
        return 0
    if isinstance(t, int):
        return t
    import datetime as dt

    from pilosa_tpu.core.timequantum import parse_time

    return int(parse_time(t).replace(tzinfo=dt.timezone.utc).timestamp())


class InternalClient:
    def __init__(self, timeout: float = 30.0, ssl_context=None,
                 retries: int = 1, breakers: Optional[BreakerRegistry] = None):
        self.timeout = timeout
        # ssl context for https:// peers (TLSConfig.client_context():
        # CA-verified or skip-verify); None = stdlib default validation.
        self.ssl_context = ssl_context
        # Transport-error retries for idempotent GETs (fragment sync,
        # status probes, federation scrapes): jittered backoff, bounded
        # by `retries` extra attempts and the active deadline. POSTs are
        # never retried here — the layers above own write retry policy.
        self.retries = max(int(retries), 0)
        # Per-peer circuit breakers: OWN instance per client (per node),
        # never shared — see breaker.py on asymmetric partitions.
        self.breakers = breakers if breakers is not None else BreakerRegistry()
        # Peer view-epoch piggyback sink (ISSUE r15 tentpole 3): every
        # response carrying X-Pilosa-View-Epochs — remote query legs,
        # replica writes — hands the parsed payload here. The cluster
        # layer installs its epoch-map fold; None drops them.
        self.on_peer_epochs = None

    # -- plumbing ----------------------------------------------------------

    def _connect_uri(self, uri: Union[URI, Node, str]) -> str:
        """The URL actually dialed for a peer. Identity (peer_label: the
        breaker key and every peer_rpc_* tag) is always derived from the
        LOGICAL uri, not this — the test harness overrides this hook to
        route one peer through a fault proxy without the proxy's port
        leaking into the peer's telemetry or breaker state."""
        return _uri_str(uri)

    def _do(
        self,
        method: str,
        uri: Union[URI, Node, str],
        path: str,
        body: Optional[bytes] = None,
        content_type: str = "application/json",
        raw: bool = False,
        op: str = "",
        want_headers: bool = False,
        extra_headers: Optional[dict] = None,
    ):
        """One RPC with bounded jittered-backoff retries for idempotent
        GETs on transport errors. Retries stop early when the peer's
        breaker just opened (the peer is systemically failing — route to
        a replica instead of burning budget here) or when the remaining
        deadline no longer covers a backoff sleep plus a dial."""
        attempts = self.retries + 1 if method == "GET" else 1
        delay = 0.05
        for attempt in range(attempts):
            try:
                return self._do_once(method, uri, path, body=body,
                                     content_type=content_type, raw=raw, op=op,
                                     want_headers=want_headers,
                                     extra_headers=extra_headers)
            except ClientError as e:
                if not e.transport or attempt + 1 >= attempts:
                    raise
                peer = peer_label(uri)
                if self.breakers.is_blocked(peer):
                    raise
                sleep = delay * (0.5 + random.random())
                d = current_deadline()
                if d is not None and d.remaining() <= sleep + 0.05:
                    raise
                count_rpc_retry(peer, op or method)
                time.sleep(sleep)
                delay = min(delay * 2, 1.0)

    def _do_once(
        self,
        method: str,
        uri: Union[URI, Node, str],
        path: str,
        body: Optional[bytes] = None,
        content_type: str = "application/json",
        raw: bool = False,
        op: str = "",
        want_headers: bool = False,
        extra_headers: Optional[dict] = None,
    ):
        url = self._connect_uri(uri) + path
        # Per-peer, per-method RPC telemetry (ISSUE r8 tentpole 2): the
        # first signal for "replica N is degraded". op is the client
        # method name (query_node, block_data, ...) — the path would
        # explode series cardinality with per-index/shard values.
        peer = peer_label(uri)
        op = op or method
        stats = global_stats.with_tags(f"peer:{peer}", f"method:{op}")
        req = urllib.request.Request(url, data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", content_type)
        req.add_header("Accept", "application/json")
        if extra_headers:
            for k, v in extra_headers.items():
                req.add_header(k, v)
        # Cross-node trace propagation (reference tracing.go:36-40): the
        # receiving node's HTTP dispatch extracts these and links its
        # spans to the coordinator's trace (VERDICT r2 weak #4: the
        # extraction side existed but nothing ever injected).
        span = global_tracer.active_span()
        if span is not None:
            for k, v in span.inject_headers().items():
                req.add_header(k, v)
        # Deadline budget (ISSUE r9 tentpole 1): the socket timeout is
        # min(client timeout, remaining budget), and the remaining budget
        # (minus a skew margin) rides the request so the peer abandons a
        # leg the coordinator has already given up on. An already-expired
        # budget fails BEFORE dialing — dispatching work nobody will wait
        # for only loads the peer.
        deadline = current_deadline()
        timeout = self.timeout
        if deadline is not None:
            if deadline.expired():
                global_stats.with_tags("phase:peer_rpc").count(
                    "deadline_exceeded_total"
                )
                raise ClientError(
                    f"{method} {url}: deadline exceeded before dispatch",
                    code="deadline-exceeded",
                )
            timeout = deadline.bound(timeout)
            req.add_header("X-Pilosa-Deadline", deadline.header_value())
        _track_inflight(peer, +1)
        t0 = time.perf_counter()
        try:
            try:
                with urllib.request.urlopen(
                    req, timeout=timeout, context=self.ssl_context
                ) as resp:
                    data = resp.read()
                    # email.message.Message: case-insensitive .get();
                    # returned to the caller only on request (checksum
                    # verification), always consulted for piggybacks.
                    resp_headers = resp.headers if want_headers else None
                    self._fold_epoch_header(resp.headers)
            except urllib.error.HTTPError as e:
                detail = ""
                err_code = ""
                try:
                    detail = e.read().decode("utf-8", "replace")
                    err_code = json.loads(detail).get("code", "")
                except (OSError, ValueError, AttributeError):
                    # Body unreadable / not JSON / not an object: the
                    # status-only ClientError below is still correct.
                    pass
                stats.with_tags(f"class:{e.code // 100}xx").count(
                    "peer_rpc_errors_total"
                )
                # An HTTP status is a live peer answering: transport is
                # healthy, whatever the answer — close the breaker.
                self.breakers.record_success(peer)
                raise ClientError(
                    f"{method} {url}: status {e.code}: {detail}",
                    status=e.code,
                    code=err_code,
                ) from e
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                stats.with_tags("class:transport").count("peer_rpc_errors_total")
                # Breaker evidence — unless the failure is a timeout this
                # request's own nearly-spent deadline manufactured: a
                # tight budget must not open the breaker against a
                # healthy-but-not-instant peer. A peer that stayed silent
                # for a FAIR window (the full client timeout, or at least
                # _FAIR_WINDOW of budget) is the peer's fault even when
                # the deadline set the socket timeout — otherwise a
                # blackholed peer under all-deadlined traffic would never
                # open its breaker (every timeout fires exactly at budget
                # expiry) and every query would keep paying a doomed leg.
                if (
                    timeout >= min(self.timeout, _FAIR_WINDOW)
                    or deadline is None
                    or deadline.remaining() > 0.01
                ):
                    self.breakers.record_failure(peer)
                raise ClientError(
                    f"{method} {url}: {e}", transport=True
                ) from e
            else:
                self.breakers.record_success(peer)
        finally:
            stats.timing("peer_rpc_seconds", time.perf_counter() - t0)
            _track_inflight(peer, -1)
        if raw:
            return (data, resp_headers) if want_headers else data
        if not data:
            return {}
        try:
            return json.loads(data)
        except json.JSONDecodeError as e:
            stats.with_tags("class:decode").count("peer_rpc_errors_total")
            raise ClientError(f"{method} {url}: invalid JSON response: {e}") from e

    def _fold_epoch_header(self, headers) -> None:
        """Parse an X-Pilosa-View-Epochs piggyback into the installed
        sink. Malformed payloads are dropped silently: the piggyback is
        an optimization plane — losing one means a cache entry ages a
        little later via the next fold, never a wrong answer (entries
        only SERVE when the map matches what was recorded)."""
        if self.on_peer_epochs is None or headers is None:
            return
        raw = headers.get("X-Pilosa-View-Epochs")
        if not raw:
            return
        try:
            payload = json.loads(raw)
        except ValueError:
            return
        if isinstance(payload, dict) and payload.get("node"):
            self.on_peer_epochs(payload)

    # -- queries (reference http/client.go QueryNode :268) -----------------

    def query_node(
        self,
        uri: Union[URI, Node, str],
        index: str,
        query: str,
        shards: Optional[Sequence[int]] = None,
        remote: bool = True,
        bypass: bool = False,
    ) -> dict:
        path = f"/index/{index}/query"
        params = []
        if shards is not None:
            params.append("shards=" + ",".join(str(s) for s in shards))
        if remote:
            params.append("remote=true")
        if params:
            path += "?" + "&".join(params)
        # A coordinator-side X-Pilosa-Cache: bypass rides every remote
        # leg: peers consult their local result caches on remote
        # executions, so the always-fresh contract must cross the node
        # boundary like the deadline does (code review r12).
        hdrs = {"X-Pilosa-Cache": "bypass"} if bypass else None
        out = self._do("POST", uri, path, query.encode(), content_type="text/plain",
                       op="query_node", extra_headers=hdrs)
        if "error" in out:
            raise ClientError(out["error"])
        return out

    # -- schema ------------------------------------------------------------

    def create_index(self, uri, index: str, options: Optional[dict] = None) -> None:
        body = json.dumps({"options": options or {}}).encode()
        self._do("POST", uri, f"/index/{index}", body, op="create_index")

    def create_field(self, uri, index: str, field: str, options: Optional[dict] = None) -> None:
        body = json.dumps({"options": options or {}}).encode()
        self._do("POST", uri, f"/index/{index}/field/{field}", body, op="create_field")

    def schema(self, uri) -> dict:
        return self._do("GET", uri, "/schema", op="schema")

    def status(self, uri) -> dict:
        return self._do("GET", uri, "/status", op="status")

    def max_shards(self, uri) -> dict:
        return self._do("GET", uri, "/internal/shards/max", op="max_shards")

    # -- imports (reference http/client.go Import/ImportRoaring) -----------

    def import_roaring(
        self,
        uri,
        index: str,
        field: str,
        shard: int,
        views: dict[str, bytes],
        clear: bool = False,
    ) -> None:
        from pilosa_tpu.server.wire import ImportRoaringRequest, ImportRoaringRequestView

        req = ImportRoaringRequest(
            clear=clear,
            views=[ImportRoaringRequestView(name, data) for name, data in views.items()],
        )
        path = f"/index/{index}/field/{field}/import-roaring/{shard}?remote=true"
        self._do("POST", uri, path, req.to_bytes(), content_type="application/x-protobuf",
                 op="import_roaring")

    def import_bits(self, uri, index: str, field: str, shard: int,
                    row_ids: Sequence[int], column_ids: Sequence[int],
                    timestamps: Optional[Sequence] = None,
                    clear: bool = False) -> None:
        """Peer-routed import: always marked remote so the receiver applies
        locally instead of re-routing (reference api.go Import forwarding)."""
        from pilosa_tpu.server.wire import ImportRequest

        req = ImportRequest(
            index=index, field=field, shard=shard,
            row_ids=list(row_ids), column_ids=list(column_ids),
            timestamps=[_ts_epoch(t) for t in timestamps] if timestamps else [],
        )
        path = f"/index/{index}/field/{field}/import?remote=true"
        if clear:
            path += "&clear=true"
        self._do("POST", uri, path, req.to_bytes(),
                 content_type="application/x-protobuf", op="import_bits")

    def import_values(self, uri, index: str, field: str, shard: int,
                      column_ids: Sequence[int], values: Sequence[int],
                      clear: bool = False) -> None:
        from pilosa_tpu.server.wire import ImportValueRequest

        req = ImportValueRequest(
            index=index, field=field, shard=shard,
            column_ids=list(column_ids), values=list(values),
        )
        path = f"/index/{index}/field/{field}/import?remote=true"
        if clear:
            path += "&clear=true"
        self._do("POST", uri, path, req.to_bytes(),
                 content_type="application/x-protobuf", op="import_values")

    # -- fragment sync (reference http/client.go:591-780) ------------------

    def fragment_blocks(self, uri, index: str, field: str, view: str, shard: int) -> list[tuple[int, int, int]]:
        """[(block, checksum, epoch)] — epoch 0 when the peer predates
        the epoch plane (rolling upgrades: an absent field degrades the
        caller to union repair, never a directed wipe)."""
        out = self._do(
            "GET", uri,
            f"/internal/fragment/blocks?index={index}&field={field}&view={view}&shard={shard}",
            op="fragment_blocks",
        )
        return [
            (int(b["id"]), int(b["checksum"]), int(b.get("epoch", 0)))
            for b in out.get("blocks", [])
        ]

    def block_data(self, uri, index: str, field: str, view: str, shard: int, block: int) -> tuple[bytes, int]:
        """One block's bytes + the epoch of exactly those bytes
        (X-Pilosa-Block-Epoch, read with the data under one fragment
        lock on the serving side — a peer write between the checksum
        snapshot and this fetch would otherwise ship post-write data
        the syncer stamps with the pre-write epoch). Epoch 0 when the
        block is epoch-unknown or the peer predates the header."""
        out = self._do(
            "GET", uri,
            f"/internal/fragment/block/data?index={index}&field={field}&view={view}"
            f"&shard={shard}&block={block}",
            raw=True,
            op="block_data",
            want_headers=True,
        )
        data, headers = out
        epoch = 0
        raw_epoch = (headers.get("X-Pilosa-Block-Epoch") or "") if headers else ""
        try:
            epoch = int(raw_epoch)
        except ValueError:
            pass
        return data, epoch

    def retrieve_shard(self, uri, index: str, field: str, view: str, shard: int) -> bytes:
        """Whole-fragment roaring payload (reference RetrieveShardFromURI
        http/client.go:742, used by resize cluster.go:1297).

        Verified (ISSUE r9 tentpole 2): the server stamps an
        X-Pilosa-Content-Checksum header and the payload is checked here
        BEFORE any caller can import_roaring it — a corrupt transfer
        raises code=checksum-mismatch so the resize fetcher retries /
        fails over instead of silently ingesting garbage. Peers too old
        to send the header skip verification (rolling-upgrade safe)."""
        import zlib

        out = self._do(
            "GET", uri,
            f"/internal/fragment/data?index={index}&field={field}&view={view}&shard={shard}",
            raw=True,
            op="retrieve_shard",
            want_headers=True,
        )
        data, headers = out
        want = (headers.get("X-Pilosa-Content-Checksum") or "") if headers else ""
        if want:
            got = f"crc32:{zlib.crc32(data) & 0xFFFFFFFF:08x}"
            if got != want:
                # Same integrity class as an unparseable JSON body: the
                # bytes that arrived are not the bytes the peer meant.
                global_stats.with_tags(
                    f"peer:{peer_label(uri)}", "method:retrieve_shard",
                    "class:decode",
                ).count("peer_rpc_errors_total")
                raise ClientError(
                    f"fragment payload checksum mismatch from "
                    f"{peer_label(uri)}: got {got}, want {want}",
                    code="checksum-mismatch",
                )
        return data

    def repair_fragment(self, uri, index: str, field: str, view: str,
                        shard: int, blocks=None) -> int:
        """Ask a replica to run one targeted epoch-directed repair pass
        on its own copy of a fragment (the read-repair plane's fan-out,
        ISSUE r15 tentpole 2). Returns the peer's repaired-block count."""
        body = json.dumps({
            "index": index, "field": field, "view": view,
            "shard": int(shard),
            "blocks": sorted(int(b) for b in blocks) if blocks else [],
        }).encode()
        out = self._do("POST", uri, "/internal/fragment/repair", body,
                       op="repair_fragment")
        return int(out.get("repaired", 0))

    def field_state(self, uri, index: str, field: str) -> dict:
        """Peer field state: view names + available shards (anti-entropy
        discovery; the reference ships this in NodeStatus gossip)."""
        return self._do(
            "GET", uri, f"/internal/field/state?index={index}&field={field}",
            op="field_state",
        )

    # -- attr sync (reference attr.go Blocks/BlockData) --------------------

    def attr_blocks(self, uri, index: str, field: Optional[str] = None) -> list[tuple[int, int]]:
        path = f"/internal/attr/blocks?index={index}"
        if field:
            path += f"&field={field}"
        out = self._do("GET", uri, path, op="attr_blocks")
        return [(int(b["id"]), int(b["checksum"])) for b in out.get("blocks", [])]

    def attr_block_data(self, uri, index: str, field: Optional[str], block: int) -> dict:
        path = f"/internal/attr/block/data?index={index}&block={block}"
        if field:
            path += f"&field={field}"
        out = self._do("GET", uri, path, op="attr_block_data")
        return {int(k): v for k, v in out.get("attrs", {}).items()}

    # -- control plane -----------------------------------------------------

    def send_message(self, uri, payload: bytes) -> None:
        self._do("POST", uri, "/internal/cluster/message", payload,
                 content_type="application/octet-stream", op="send_message")

    def export_csv_shard(self, uri, index: str, field: str, shard: int) -> str:
        """One shard's CSV from the node that holds it (whole-field
        export fans out through this; reference ctl/export.go)."""
        from urllib.parse import quote

        raw = self._do(
            "GET", uri,
            f"/export?index={quote(index)}&field={quote(field)}&shard={shard}",
            raw=True,
            op="export_csv_shard",
        )
        return raw.decode()

    # -- translation -------------------------------------------------------

    def translate_keys(self, uri, index: str, field: str, keys: Sequence[str]) -> list[int]:
        body = json.dumps({"index": index, "field": field, "keys": list(keys)}).encode()
        out = self._do("POST", uri, "/internal/translate/keys", body,
                       op="translate_keys")
        return [int(v) for v in out.get("ids", [])]

    def translate_data(self, uri, index: str, field: str = "", offset: int = 0) -> list:
        out = self._do(
            "GET", uri,
            f"/internal/translate/data?index={index}&field={field}&offset={offset}",
            op="translate_data",
        )
        return out.get("entries", [])

    # -- observability plane (ISSUE r8) ------------------------------------

    def node_traces(self, uri, trace_id: str) -> list[dict]:
        """One node's local spans for a trace — the per-node leg of
        /debug/traces/<id> distributed assembly."""
        out = self._do("GET", uri, f"/internal/traces/{trace_id}",
                       op="node_traces")
        return out.get("spans", [])

    def metrics_text(self, uri) -> str:
        """One node's raw prometheus exposition — the federation scrape
        behind /metrics/cluster. ?exemplars=1 opts into the OpenMetrics
        exemplar suffixes so the re-tagged per-node series keep their
        trace links (the federation response strips them again for any
        scraper that didn't opt in itself)."""
        return self._do("GET", uri, "/metrics?exemplars=1", raw=True,
                        op="metrics_text").decode("utf-8", "replace")

    def debug_vars(self, uri) -> dict:
        """One node's expvar-style registry dump (/debug/cluster leg)."""
        return self._do("GET", uri, "/debug/vars", op="debug_vars")
