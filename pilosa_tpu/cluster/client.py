"""Internal peer-to-peer HTTP client (reference http/client.go InternalClient).

The DCN data plane: query fan-out (QueryNode with shards pinned +
remote=true, reference http/client.go:268), imports, fragment block sync
for anti-entropy, whole-fragment retrieval for resize, control-plane
message delivery, and key-translation RPCs. stdlib urllib with persistent
behavior left to the OS; every call raises ClientError on transport or
HTTP-status failure so the scatter-gather layer can retry replicas.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional, Sequence, Union

from pilosa_tpu.cluster.topology import URI, Node
from pilosa_tpu.utils.tracing import global_tracer


class ClientError(Exception):
    def __init__(self, msg: str, status: int = 0, code: str = ""):
        super().__init__(msg)
        self.status = status
        # Machine-readable error class from the peer's JSON error body
        # (e.g. "not-found"); empty when the body carried none.
        self.code = code


def _uri_str(uri: Union[URI, Node, str]) -> str:
    if isinstance(uri, Node):
        uri = uri.uri
    return str(uri)


def _ts_epoch(t) -> int:
    """Timestamp (int seconds / PQL string / datetime / falsy) -> unix
    seconds for the wire (reference ImportRequest.Timestamps int64)."""
    if not t:
        return 0
    if isinstance(t, int):
        return t
    import datetime as dt

    from pilosa_tpu.core.timequantum import parse_time

    return int(parse_time(t).replace(tzinfo=dt.timezone.utc).timestamp())


class InternalClient:
    def __init__(self, timeout: float = 30.0, ssl_context=None):
        self.timeout = timeout
        # ssl context for https:// peers (TLSConfig.client_context():
        # CA-verified or skip-verify); None = stdlib default validation.
        self.ssl_context = ssl_context

    # -- plumbing ----------------------------------------------------------

    def _do(
        self,
        method: str,
        uri: Union[URI, Node, str],
        path: str,
        body: Optional[bytes] = None,
        content_type: str = "application/json",
        raw: bool = False,
    ):
        url = _uri_str(uri) + path
        req = urllib.request.Request(url, data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", content_type)
        req.add_header("Accept", "application/json")
        # Cross-node trace propagation (reference tracing.go:36-40): the
        # receiving node's HTTP dispatch extracts these and links its
        # spans to the coordinator's trace (VERDICT r2 weak #4: the
        # extraction side existed but nothing ever injected).
        span = global_tracer.active_span()
        if span is not None:
            for k, v in span.inject_headers().items():
                req.add_header(k, v)
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout, context=self.ssl_context
            ) as resp:
                data = resp.read()
        except urllib.error.HTTPError as e:
            detail = ""
            err_code = ""
            try:
                detail = e.read().decode("utf-8", "replace")
                err_code = json.loads(detail).get("code", "")
            except Exception:
                pass
            raise ClientError(
                f"{method} {url}: status {e.code}: {detail}",
                status=e.code,
                code=err_code,
            ) from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise ClientError(f"{method} {url}: {e}") from e
        if raw:
            return data
        if not data:
            return {}
        try:
            return json.loads(data)
        except json.JSONDecodeError as e:
            raise ClientError(f"{method} {url}: invalid JSON response: {e}") from e

    # -- queries (reference http/client.go QueryNode :268) -----------------

    def query_node(
        self,
        uri: Union[URI, Node, str],
        index: str,
        query: str,
        shards: Optional[Sequence[int]] = None,
        remote: bool = True,
    ) -> dict:
        path = f"/index/{index}/query"
        params = []
        if shards is not None:
            params.append("shards=" + ",".join(str(s) for s in shards))
        if remote:
            params.append("remote=true")
        if params:
            path += "?" + "&".join(params)
        out = self._do("POST", uri, path, query.encode(), content_type="text/plain")
        if "error" in out:
            raise ClientError(out["error"])
        return out

    # -- schema ------------------------------------------------------------

    def create_index(self, uri, index: str, options: Optional[dict] = None) -> None:
        body = json.dumps({"options": options or {}}).encode()
        self._do("POST", uri, f"/index/{index}", body)

    def create_field(self, uri, index: str, field: str, options: Optional[dict] = None) -> None:
        body = json.dumps({"options": options or {}}).encode()
        self._do("POST", uri, f"/index/{index}/field/{field}", body)

    def schema(self, uri) -> dict:
        return self._do("GET", uri, "/schema")

    def status(self, uri) -> dict:
        return self._do("GET", uri, "/status")

    def max_shards(self, uri) -> dict:
        return self._do("GET", uri, "/internal/shards/max")

    # -- imports (reference http/client.go Import/ImportRoaring) -----------

    def import_roaring(
        self,
        uri,
        index: str,
        field: str,
        shard: int,
        views: dict[str, bytes],
        clear: bool = False,
    ) -> None:
        from pilosa_tpu.server.wire import ImportRoaringRequest, ImportRoaringRequestView

        req = ImportRoaringRequest(
            clear=clear,
            views=[ImportRoaringRequestView(name, data) for name, data in views.items()],
        )
        path = f"/index/{index}/field/{field}/import-roaring/{shard}?remote=true"
        self._do("POST", uri, path, req.to_bytes(), content_type="application/x-protobuf")

    def import_bits(self, uri, index: str, field: str, shard: int,
                    row_ids: Sequence[int], column_ids: Sequence[int],
                    timestamps: Optional[Sequence] = None,
                    clear: bool = False) -> None:
        """Peer-routed import: always marked remote so the receiver applies
        locally instead of re-routing (reference api.go Import forwarding)."""
        from pilosa_tpu.server.wire import ImportRequest

        req = ImportRequest(
            index=index, field=field, shard=shard,
            row_ids=list(row_ids), column_ids=list(column_ids),
            timestamps=[_ts_epoch(t) for t in timestamps] if timestamps else [],
        )
        path = f"/index/{index}/field/{field}/import?remote=true"
        if clear:
            path += "&clear=true"
        self._do("POST", uri, path, req.to_bytes(), content_type="application/x-protobuf")

    def import_values(self, uri, index: str, field: str, shard: int,
                      column_ids: Sequence[int], values: Sequence[int],
                      clear: bool = False) -> None:
        from pilosa_tpu.server.wire import ImportValueRequest

        req = ImportValueRequest(
            index=index, field=field, shard=shard,
            column_ids=list(column_ids), values=list(values),
        )
        path = f"/index/{index}/field/{field}/import?remote=true"
        if clear:
            path += "&clear=true"
        self._do("POST", uri, path, req.to_bytes(), content_type="application/x-protobuf")

    # -- fragment sync (reference http/client.go:591-780) ------------------

    def fragment_blocks(self, uri, index: str, field: str, view: str, shard: int) -> list[tuple[int, int]]:
        out = self._do(
            "GET", uri,
            f"/internal/fragment/blocks?index={index}&field={field}&view={view}&shard={shard}",
        )
        return [(int(b["id"]), int(b["checksum"])) for b in out.get("blocks", [])]

    def block_data(self, uri, index: str, field: str, view: str, shard: int, block: int) -> bytes:
        return self._do(
            "GET", uri,
            f"/internal/fragment/block/data?index={index}&field={field}&view={view}"
            f"&shard={shard}&block={block}",
            raw=True,
        )

    def retrieve_shard(self, uri, index: str, field: str, view: str, shard: int) -> bytes:
        """Whole-fragment roaring payload (reference RetrieveShardFromURI
        http/client.go:742, used by resize cluster.go:1297)."""
        return self._do(
            "GET", uri,
            f"/internal/fragment/data?index={index}&field={field}&view={view}&shard={shard}",
            raw=True,
        )

    def field_state(self, uri, index: str, field: str) -> dict:
        """Peer field state: view names + available shards (anti-entropy
        discovery; the reference ships this in NodeStatus gossip)."""
        return self._do(
            "GET", uri, f"/internal/field/state?index={index}&field={field}"
        )

    # -- attr sync (reference attr.go Blocks/BlockData) --------------------

    def attr_blocks(self, uri, index: str, field: Optional[str] = None) -> list[tuple[int, int]]:
        path = f"/internal/attr/blocks?index={index}"
        if field:
            path += f"&field={field}"
        out = self._do("GET", uri, path)
        return [(int(b["id"]), int(b["checksum"])) for b in out.get("blocks", [])]

    def attr_block_data(self, uri, index: str, field: Optional[str], block: int) -> dict:
        path = f"/internal/attr/block/data?index={index}&block={block}"
        if field:
            path += f"&field={field}"
        out = self._do("GET", uri, path)
        return {int(k): v for k, v in out.get("attrs", {}).items()}

    # -- control plane -----------------------------------------------------

    def send_message(self, uri, payload: bytes) -> None:
        self._do("POST", uri, "/internal/cluster/message", payload,
                 content_type="application/octet-stream")

    def export_csv_shard(self, uri, index: str, field: str, shard: int) -> str:
        """One shard's CSV from the node that holds it (whole-field
        export fans out through this; reference ctl/export.go)."""
        from urllib.parse import quote

        raw = self._do(
            "GET", uri,
            f"/export?index={quote(index)}&field={quote(field)}&shard={shard}",
            raw=True,
        )
        return raw.decode()

    # -- translation -------------------------------------------------------

    def translate_keys(self, uri, index: str, field: str, keys: Sequence[str]) -> list[int]:
        body = json.dumps({"index": index, "field": field, "keys": list(keys)}).encode()
        out = self._do("POST", uri, "/internal/translate/keys", body)
        return [int(v) for v in out.get("ids", [])]

    def translate_data(self, uri, index: str, field: str = "", offset: int = 0) -> list:
        out = self._do(
            "GET", uri,
            f"/internal/translate/data?index={index}&field={field}&offset={offset}",
        )
        return out.get("entries", [])
