"""Cluster topology: URIs, nodes, partitioning, consistent hashing.

Mirrors the reference's placement math exactly (cluster.go:871-959) so a
dataset sharded by this framework lands on the same nodes the reference
would pick: shard -> partition via fnv64a over (index name, big-endian
shard) mod partitionN (default 256), partition -> primary node via
jump-consistent-hash over the ID-sorted node list, replicas on the next
ReplicaN-1 ring positions.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional, Sequence

from pilosa_tpu.native import fnv64a

DEFAULT_PARTITION_N = 256  # reference cluster.go:44

# Cluster states (reference cluster.go:47-50).
STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_DEGRADED = "DEGRADED"
STATE_RESIZING = "RESIZING"

# Node states during resize (reference node.go).
NODE_STATE_READY = "READY"
NODE_STATE_DOWN = "DOWN"

_URI_RE = re.compile(
    r"^(?:(?P<scheme>[a-zA-Z][a-zA-Z0-9+.-]*)://)?(?P<host>[^:/]+)?(?::(?P<port>\d+))?$"
)


@dataclass(frozen=True)
class URI:
    """scheme://host:port node address (reference uri.go)."""

    scheme: str = "http"
    host: str = "localhost"
    port: int = 10101

    @staticmethod
    def parse(s: str) -> "URI":
        m = _URI_RE.match(s.strip())
        if not m:
            raise ValueError(f"invalid URI: {s!r}")
        return URI(
            scheme=m.group("scheme") or "http",
            host=m.group("host") or "localhost",
            port=int(m.group("port") or 10101),
        )

    def __str__(self) -> str:
        return f"{self.scheme}://{self.host}:{self.port}"

    @property
    def host_port(self) -> str:
        return f"{self.host}:{self.port}"


@dataclass
class Node:
    """Cluster member (reference node.go Node)."""

    id: str
    uri: URI
    is_coordinator: bool = False
    state: str = NODE_STATE_READY

    def to_json(self) -> dict:
        return {
            "id": self.id,
            "uri": {
                "scheme": self.uri.scheme,
                "host": self.uri.host,
                "port": self.uri.port,
            },
            "isCoordinator": self.is_coordinator,
            "state": self.state,
        }

    @staticmethod
    def from_json(d: dict) -> "Node":
        u = d.get("uri") or {}
        return Node(
            id=d["id"],
            uri=URI(
                scheme=u.get("scheme", "http"),
                host=u.get("host", "localhost"),
                port=int(u.get("port", 10101)),
            ),
            is_coordinator=bool(d.get("isCoordinator")),
            state=d.get("state", NODE_STATE_READY),
        )


class JmpHasher:
    """Jump consistent hash (reference cluster.go:947-959)."""

    @staticmethod
    def hash(key: int, n: int) -> int:
        key &= (1 << 64) - 1
        b, j = -1, 0
        while j < n:
            b = j
            key = (key * 2862933555777941757 + 1) & ((1 << 64) - 1)
            j = int(float(b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
        return b


class ModHasher:
    """Deterministic key % n hasher for tests (reference test/cluster.go:18)."""

    @staticmethod
    def hash(key: int, n: int) -> int:
        return key % n


class Topology:
    """Pure placement math over an ID-sorted node list.

    Separated from Cluster so resize planning can diff two topologies
    (reference cluster.fragSources cluster.go:784 compares old/new node
    sets through the same partition functions).
    """

    def __init__(
        self,
        nodes: Optional[Sequence[Node]] = None,
        replica_n: int = 1,
        partition_n: int = DEFAULT_PARTITION_N,
        hasher=None,
    ):
        self.nodes: list[Node] = sorted(nodes or [], key=lambda n: n.id)
        self.replica_n = replica_n
        self.partition_n = partition_n
        self.hasher = hasher or JmpHasher()

    # -- membership --------------------------------------------------------

    def add_node(self, node: Node) -> None:
        if self.node_by_id(node.id) is None:
            self.nodes.append(node)
            self.nodes.sort(key=lambda n: n.id)

    def remove_node(self, node_id: str) -> bool:
        n = self.node_by_id(node_id)
        if n is None:
            return False
        self.nodes.remove(n)
        return True

    def node_by_id(self, node_id: str) -> Optional[Node]:
        for n in self.nodes:
            if n.id == node_id:
                return n
        return None

    # -- placement (reference cluster.go:871-959) --------------------------

    def partition(self, index: str, shard: int) -> int:
        buf = index.encode() + shard.to_bytes(8, "big")
        return fnv64a(buf) % self.partition_n

    def partition_nodes(self, partition_id: int) -> list[Node]:
        if not self.nodes:
            return []
        replica_n = min(max(self.replica_n, 1), len(self.nodes))
        node_index = self.hasher.hash(partition_id, len(self.nodes))
        return [self.nodes[(node_index + i) % len(self.nodes)] for i in range(replica_n)]

    def shard_nodes(self, index: str, shard: int) -> list[Node]:
        return self.partition_nodes(self.partition(index, shard))

    def owns_shard(self, node_id: str, index: str, shard: int) -> bool:
        return any(n.id == node_id for n in self.shard_nodes(index, shard))

    def primary_for_shard(self, index: str, shard: int) -> Optional[Node]:
        nodes = self.shard_nodes(index, shard)
        return nodes[0] if nodes else None

    def contains_shards(self, index: str, shards: Sequence[int], node: Node) -> list[int]:
        """Shards owned by node incl. replicas (reference containsShards :926)."""
        out = []
        for s in shards:
            if any(n.id == node.id for n in self.shard_nodes(index, s)):
                out.append(s)
        return out


# ---------------------------------------------------------------------------
# persisted topology (ISSUE r9 tentpole 3)
# ---------------------------------------------------------------------------

#: File name under the data dir; the reference persists .topology the
#: same way (topology.go encode/decode via holder.loadTopology).
TOPOLOGY_FILE = ".topology"


def save_topology(path: str, topology: Topology, local_id: str,
                  resize_epoch: int = 0) -> None:
    """Atomically persist membership (nodes, replicaN, partitionN) plus
    this node's identity and the resize epoch. tmp + os.replace: a crash
    mid-write leaves either the old complete file or the new complete
    file, never a torn prefix (the PR 8 durable-write discipline — the
    lint rule covers this package)."""
    import json
    import os

    blob = json.dumps(
        {
            "localID": local_id,
            "replicaN": topology.replica_n,
            "partitionN": topology.partition_n,
            "resizeEpoch": int(resize_epoch),
            "nodes": [n.to_json() for n in topology.nodes],
        }
    )
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(blob + "\n")
    os.replace(tmp, path)


def load_topology(path: str) -> Optional[dict]:
    """The persisted topology dict, or None when the file is absent,
    unparseable, or structurally invalid (a corrupt topology file must
    degrade to 'seed me again', never crash the boot — the operator's
    config still works). Every node entry must round-trip through
    Node.from_json, so callers can construct Nodes without guarding."""
    import json

    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(d, dict) or not isinstance(d.get("nodes"), list):
        return None
    try:
        for entry in d["nodes"]:
            Node.from_json(entry)
    except (TypeError, KeyError, ValueError, AttributeError):
        return None  # truncated / hand-mangled node entries
    return d
