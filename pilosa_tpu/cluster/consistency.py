"""Read-path replica divergence detection + targeted read repair
(ISSUE r15 tentpole 2).

A hedged shard read that gets answers from TWO replicas is a free
consistency probe: the serving path hands the pair to this monitor (one
bounded-queue append, never any comparison work on the request thread),
and a background worker diffs the replicas' per-fragment block
checksums for the touched shards. Disagreement is counted per index
(`replica_divergence_blocks_total{index}`), recorded on a ledger served
at `GET /debug/consistency` (ordered by staleness: oldest unrepaired
divergence first), and healed by asking BOTH replicas to run a
targeted epoch-directed repair pass over exactly the differing blocks
(`/internal/fragment/repair` -> HolderSyncer.sync_fragment_targeted) —
each side pulls the higher-epoch blocks from the other, so the pair
converges without waiting for the next full anti-entropy sweep.

The queue is bounded (`read-repair-queue` config knob): under a
divergence storm the serving path stays O(1) and overflow is counted
(`read_repair_dropped_total`) rather than buffered — the periodic
anti-entropy sweep is the backstop for anything dropped here.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from pilosa_tpu.cluster.client import ClientError
from pilosa_tpu.utils.logger import NopLogger
from pilosa_tpu.utils.stats import global_stats

#: Ledger bound: recent divergence observations kept for
#: /debug/consistency. Repaired entries age out first.
LEDGER_MAX = 256

#: Per-probe RPC budget (seconds): checksum fetches + repair fan-out
#: for one observation. A stalled replica costs the worker at most one
#: budget, not a wedge.
PROBE_BUDGET = 30.0


def call_fields(c):
    """Best-effort field names a PQL call tree reads, for scoping a
    divergence probe to the fragments the hedged read actually
    witnessed (diffing EVERY field of a wide index per observation
    multiplies peer RPC load by schema width for fields the read never
    touched — whole-index coverage is the periodic sweep's job). None =
    couldn't positively identify every field (unknown call shape):
    the probe falls back to all fields, never silently under-covers."""
    out: set = set()
    stack = [c]
    while stack:
        node = stack.pop()
        name = getattr(node, "name", "")
        args = getattr(node, "args", None) or {}
        if name == "Row":
            for arg in args:
                if not arg.startswith("_"):
                    out.add(arg)
                    break
        elif name in ("Rows", "TopN"):
            fn = args.get("_field") or args.get("field")
            if not fn:
                return None
            out.add(fn)
        elif name in ("Sum", "Min", "Max"):
            fn = args.get("field")
            if not fn:
                for arg in args:
                    if not arg.startswith("_"):
                        fn = arg
                        break
            if not fn:
                return None
            out.add(fn)
        elif name in ("Count", "Intersect", "Union", "Difference",
                      "Xor", "Not", "GroupBy", "All"):
            pass  # container calls: fields come from their children
        else:
            return None  # unknown shape: don't guess, probe everything
        for v in args.values():
            if hasattr(v, "name") and hasattr(v, "args"):
                stack.append(v)
        stack.extend(getattr(node, "children", ()) or ())
    return frozenset(out) if out else None


class DivergenceMonitor:
    """Bounded-queue background consistency checker."""

    def __init__(self, cluster, max_queue: int = 128, logger=None):
        self.cluster = cluster
        self.max_queue = max(int(max_queue), 1)
        self.log = logger or NopLogger()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._ledger: deque = deque(maxlen=LEDGER_MAX)
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        cluster.divergence = self

    # -- serving-path hook (O(1), lock held for an append only) ------------

    def observe(self, index: str, shards, node_a: str, node_b: str,
                fields=None) -> None:
        """One hedge race produced answers from two replicas: queue the
        pair for a background checksum diff. Never blocks the serving
        path — a full queue drops the probe (counted; the anti-entropy
        sweep remains the backstop). `fields` (frozenset) scopes the
        diff to the fields the read touched; None probes every field."""
        probe = (index, tuple(sorted(set(shards))), node_a, node_b, fields)
        with self._cv:
            if probe in self._queue:
                # A hot hedged pair re-observed while its probe is
                # still pending: re-diffing it back to back buys
                # nothing and starves genuinely new observations out of
                # the bounded queue. O(queue) scan, queue <= max_queue.
                return
            if len(self._queue) >= self.max_queue:
                global_stats.count("read_repair_dropped_total")
                return
            self._queue.append(probe)
            global_stats.gauge("read_repair_pending", len(self._queue))
            self._cv.notify()
        global_stats.count("read_repair_enqueued_total")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "DivergenceMonitor":
        from pilosa_tpu.utils.threads import spawn

        self._thread = spawn(
            "divergence-monitor", self._run, name="divergence-monitor"
        )
        return self

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    # lint: allow-lock-discipline(canonical Condition.wait: it RELEASES the condition lock while blocked; observers only ever append under it)
                    self._cv.wait(1.0)
                if self._stop:
                    return
                probe = self._queue.popleft()
                global_stats.gauge("read_repair_pending", len(self._queue))
            try:
                self._check(*probe)
            except Exception as e:  # noqa: BLE001 — counted crash barrier
                global_stats.count("read_repair_errors_total")
                self.log.printf("divergence probe failed: %s", e)

    def drain(self, timeout: float = 5.0) -> bool:
        """Test/bench barrier: True once the queue is empty and the
        worker is idle (best-effort — the queue length is the signal)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue:
                    return True
            time.sleep(0.01)
        return False

    # -- the probe ----------------------------------------------------------

    def _node(self, node_id: str):
        return self.cluster.topology.node_by_id(node_id)

    def _check(self, index: str, shards, id_a: str, id_b: str,
               fields=None) -> None:
        """Diff the two replicas' block checksums for every fragment of
        the touched shards (scoped to `fields` when the observation
        names them); divergent blocks land on the ledger and both
        replicas are asked to repair exactly those blocks."""
        from pilosa_tpu.utils.deadline import Deadline, deadline_scope

        holder = self.cluster.holder
        a, b = self._node(id_a), self._node(id_b)
        if holder is None or a is None or b is None:
            return
        with deadline_scope(Deadline(PROBE_BUDGET)):
            for index_name, field_name, view_name, shard in self._fragments(
                holder, index, shards, fields
            ):
                self._check_fragment(
                    index_name, field_name, view_name, shard, a, b
                )

    @staticmethod
    def _fragments(holder, index: str, shards, fields=None):
        idx = holder.index(index)
        if idx is None:
            return
        for fname in list(idx.fields):
            if fields is not None and fname not in fields:
                continue
            f = idx.field(fname)
            if f is None:
                continue
            for vname in list(f.views):
                for shard in shards:
                    yield index, fname, vname, shard

    def _check_fragment(self, index, field, view, shard, a, b) -> None:
        client = self.cluster.client

        def fetch(node):
            # A 404 is a DECISION — this replica simply has no such
            # fragment, which against a peer that does is the LARGEST
            # possible divergence (it missed every write), so it must
            # be diffed as an empty block set, counted, and ledgered —
            # not silently skipped. Transport failures stay a skip: we
            # can't judge what we can't reach.
            try:
                return client.fragment_blocks(node, index, field, view, shard)
            except ClientError as e:
                if e.status == 404:
                    return []
                raise

        try:
            blocks_a = fetch(a)
            blocks_b = fetch(b)
        except ClientError:
            return  # unreachable replica: the sweep backstops
        map_a = {blk: s for blk, s, _e in blocks_a}
        map_b = {blk: s for blk, s, _e in blocks_b}
        diff = sorted(
            blk
            for blk in set(map_a) | set(map_b)
            if map_a.get(blk, 0) != map_b.get(blk, 0)
        )
        if not diff:
            return
        global_stats.with_tags(f"index:{index}").count(
            "replica_divergence_blocks_total", len(diff)
        )
        entry = {
            "index": index,
            "field": field,
            "view": view,
            "shard": int(shard),
            "blocks": diff,
            "nodes": [a.id, b.id],
            "detected_mono": time.monotonic(),
            "repaired": False,
            "repairedBlocks": 0,
        }
        with self._lock:
            self._ledger.append(entry)
        # Targeted heal: each replica pulls the higher-epoch blocks from
        # its peers for exactly these blocks. Best-effort — a failed
        # repair leaves the ledger entry unrepaired (staleness-ordered
        # at the top of /debug/consistency) and the sweep backstops.
        repaired = 0
        for node in (a, b):
            try:
                repaired += client.repair_fragment(
                    node, index, field, view, shard, blocks=diff
                )
            except ClientError as e:
                self.log.printf(
                    "read repair on %s %s/%s/%s/%s failed: %s",
                    node.id, index, field, view, shard, e,
                )
        with self._lock:
            entry["repaired"] = repaired > 0
            entry["repairedBlocks"] = repaired

    # -- /debug/consistency --------------------------------------------------

    def debug_dump(self) -> dict:
        """Ledger ordered by staleness: unrepaired divergences first,
        oldest first — the top row is the longest-standing known
        inconsistency (mirroring /debug/hbm's coldest-first)."""
        now = time.monotonic()
        with self._lock:
            entries = [dict(e) for e in self._ledger]
            pending = len(self._queue)
        for e in entries:
            e["ageSeconds"] = round(now - e.pop("detected_mono"), 3)
        entries.sort(key=lambda e: (e["repaired"], -e["ageSeconds"]))
        return {
            "enabled": True,
            "pendingProbes": pending,
            "maxQueue": self.max_queue,
            "entries": entries,
        }
