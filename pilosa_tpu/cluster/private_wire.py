"""Typed binary control plane (reference internal/private.proto:5-195 +
encoding/proto/proto.go:29-42).

The reference moves cluster-control traffic — resize instructions
(carrying whole schemas), cluster status, node events — as versioned
protobuf messages behind a Serializer seam, with a 1-byte type prefix
on the broadcast wire (broadcast.go:55-122). This module is that seam's
binary implementation: hand-rolled protobuf wire format (same varint
codec style as server/wire.py's public.proto messages) for every
control message the bus carries. The in-process representation stays
the broadcast.Message dict; marshal/unmarshal convert at the wire so
the cluster protocol can evolve behind explicit field numbers instead
of ad-hoc JSON key spellings.

Frame layout: [type byte][version byte][protobuf body]. Type bytes
deliberately start at 0x01 and never collide with '{' (0x7B), so a
receiver can sniff legacy-JSON frames from old peers.

Compatibility directions: old→new works transparently (JSON sniff);
frames from a NEWER peer (unknown type byte or version) decode to an
ignorable "unknown-wire-*" message so the receive dispatch skips them
instead of erroring. new→old does NOT work automatically — an
old JSON-only peer cannot parse binary frames — so rolling upgrades
across the serializer boundary should run the sender in JSON mode
(PILOSA_TPU_CONTROL_WIRE=json) until every node is upgraded.

Message ↔ type byte registry at the bottom; unknown/untyped message
types marshal as JSON transparently.
"""

from __future__ import annotations

import json

from pilosa_tpu.server.wire import (
    _encode_bool,
    _encode_bytes,
    _encode_packed_uint64,
    _encode_string,
    _encode_uint64,
    _encode_varint,
    _field_str,
    _iter_fields,
    _repeated_uint64,
)

WIRE_VERSION = 1


def _encode_sint64(fnum: int, v: int) -> bytes:
    """zigzag-encoded signed int (BSI min/max/base can be negative)."""
    zz = (v << 1) ^ (v >> 63) if v >= 0 else ((-v) << 1) - 1
    return _encode_varint(fnum << 3) + _encode_varint(zz & ((1 << 64) - 1))


def _decode_sint(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


# -- Node ----------------------------------------------------------------


def _enc_node(n: dict) -> bytes:
    uri = n.get("uri") or {}
    out = _encode_string(1, n.get("id", ""))
    out += _encode_string(2, uri.get("scheme", "http"))
    out += _encode_string(3, uri.get("host", "localhost"))
    out += _encode_uint64(4, int(uri.get("port", 10101)))
    out += _encode_bool(5, bool(n.get("isCoordinator")))
    out += _encode_string(6, n.get("state", "READY"))
    return out


def _dec_node(data: bytes) -> dict:
    n = {"id": "", "uri": {"scheme": "http", "host": "localhost", "port": 10101},
         "isCoordinator": False, "state": "READY"}
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            n["id"] = _field_str(v)
        elif fnum == 2:
            n["uri"]["scheme"] = _field_str(v)
        elif fnum == 3:
            n["uri"]["host"] = _field_str(v)
        elif fnum == 4:
            n["uri"]["port"] = int(v)
        elif fnum == 5:
            n["isCoordinator"] = bool(v)
        elif fnum == 6:
            n["state"] = _field_str(v)
    return n


# -- Schema (IndexMeta/FieldMeta, private.proto) ---------------------------


def _enc_field_options(o: dict) -> bytes:
    out = _encode_string(1, o.get("type", "set"))
    out += _encode_string(2, o.get("cacheType", ""))
    out += _encode_uint64(3, int(o.get("cacheSize", 0)))
    out += _encode_sint64(4, int(o.get("min", 0)))
    out += _encode_sint64(5, int(o.get("max", 0)))
    out += _encode_sint64(6, int(o.get("base", 0)))
    out += _encode_uint64(7, int(o.get("bitDepth", 0)))
    out += _encode_string(8, o.get("timeQuantum", "") or "")
    out += _encode_bool(9, bool(o.get("keys")))
    out += _encode_bool(10, bool(o.get("noStandardView")))
    return out


def _dec_field_options(data: bytes) -> dict:
    o = {"type": "set", "cacheType": "", "cacheSize": 0, "min": 0, "max": 0,
         "base": 0, "bitDepth": 0, "timeQuantum": "", "keys": False,
         "noStandardView": False}
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            o["type"] = _field_str(v)
        elif fnum == 2:
            o["cacheType"] = _field_str(v)
        elif fnum == 3:
            o["cacheSize"] = int(v)
        elif fnum == 4:
            o["min"] = _decode_sint(int(v))
        elif fnum == 5:
            o["max"] = _decode_sint(int(v))
        elif fnum == 6:
            o["base"] = _decode_sint(int(v))
        elif fnum == 7:
            o["bitDepth"] = int(v)
        elif fnum == 8:
            o["timeQuantum"] = _field_str(v)
        elif fnum == 9:
            o["keys"] = bool(v)
        elif fnum == 10:
            o["noStandardView"] = bool(v)
    return o


def _enc_field(f: dict) -> bytes:
    out = _encode_string(1, f.get("name", ""))
    out += _encode_bytes(2, _enc_field_options(f.get("options") or {}))
    return out


def _dec_field(data: bytes) -> dict:
    f = {"name": "", "options": {}}
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            f["name"] = _field_str(v)
        elif fnum == 2:
            f["options"] = _dec_field_options(v)
    return f


def _enc_index(i: dict) -> bytes:
    opts = i.get("options") or {}
    out = _encode_string(1, i.get("name", ""))
    out += _encode_bool(2, bool(opts.get("keys")))
    out += _encode_bool(3, bool(opts.get("trackExistence", True)))
    for f in i.get("fields") or []:
        out += _encode_bytes(4, _enc_field(f))
    out += _encode_uint64(5, int(i.get("shardWidth", 0)))
    return out


def _dec_index(data: bytes) -> dict:
    i = {"name": "", "options": {"keys": False, "trackExistence": True},
         "fields": []}
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            i["name"] = _field_str(v)
        elif fnum == 2:
            i["options"]["keys"] = bool(v)
        elif fnum == 3:
            i["options"]["trackExistence"] = bool(v)
        elif fnum == 4:
            i["fields"].append(_dec_field(v))
        elif fnum == 5 and int(v):
            i["shardWidth"] = int(v)
    return i


def _enc_schema(s: dict) -> bytes:
    out = b""
    for idx in (s or {}).get("indexes") or []:
        out += _encode_bytes(1, _enc_index(idx))
    return out


def _dec_schema(data: bytes) -> dict:
    return {"indexes": [_dec_index(v) for fnum, _w, v in _iter_fields(data)
                        if fnum == 1]}


# -- available-shards map + resize sources ---------------------------------


def _enc_avail(available: dict) -> bytes:
    """{index: {field: [shards]}} as repeated FieldAvail submessages."""
    out = b""
    for iname, fields in (available or {}).items():
        for fname, shards in fields.items():
            body = _encode_string(1, iname)
            body += _encode_string(2, fname)
            body += _encode_packed_uint64(3, [int(s) for s in shards])
            out += _encode_bytes(15, body)
    return out


def _dec_avail_entry(data: bytes, into: dict) -> None:
    iname = fname = ""
    shards: list[int] = []
    for fnum, w, v in _iter_fields(data):
        if fnum == 1:
            iname = _field_str(v)
        elif fnum == 2:
            fname = _field_str(v)
        elif fnum == 3:
            shards.extend(_repeated_uint64(v, w))
    into.setdefault(iname, {})[fname] = shards


def _enc_source(src: dict) -> bytes:
    out = _encode_string(1, src.get("index", ""))
    out += _encode_string(2, src.get("field", ""))
    out += _encode_uint64(3, int(src.get("shard", 0)))
    out += _encode_string(4, str(src.get("from", "")))
    # Alternate surviving owners the fetcher fails over to (ISSUE r9);
    # repeated string, absent on frames from older builds.
    for alt in src.get("alts") or []:
        out += _encode_string(5, str(alt))
    return out


def _dec_source(data: bytes) -> dict:
    src: dict = {"index": "", "field": "", "shard": 0, "from": "", "alts": []}
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            src["index"] = _field_str(v)
        elif fnum == 2:
            src["field"] = _field_str(v)
        elif fnum == 3:
            src["shard"] = int(v)
        elif fnum == 4:
            src["from"] = _field_str(v)
        elif fnum == 5:
            src["alts"].append(_field_str(v))
    return src


# -- per-message-type bodies ------------------------------------------------
# Each entry: (type_byte, encode(msg)->bytes, decode(bytes)->fields dict).


def _enc_create_shard(m: dict) -> bytes:
    return (_encode_string(1, m.get("index", ""))
            + _encode_string(2, m.get("field", ""))
            + _encode_uint64(3, int(m.get("shard", 0))))


def _dec_create_shard(data: bytes) -> dict:
    m = {"index": "", "field": "", "shard": 0}
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            m["index"] = _field_str(v)
        elif fnum == 2:
            m["field"] = _field_str(v)
        elif fnum == 3:
            m["shard"] = int(v)
    return m


def _enc_cluster_status(m: dict) -> bytes:
    out = _encode_string(1, m.get("state", ""))
    for n in m.get("nodes") or []:
        out += _encode_bytes(2, _enc_node(n))
    if "replicaN" in m:
        out += _encode_uint64(3, int(m["replicaN"]))
    # presence marker for nodes: an empty node list must stay absent
    out += _encode_bool(4, "nodes" in m)
    return out


def _dec_cluster_status(data: bytes) -> dict:
    m: dict = {"state": ""}
    nodes = []
    has_nodes = False
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            m["state"] = _field_str(v)
        elif fnum == 2:
            nodes.append(_dec_node(v))
        elif fnum == 3:
            m["replicaN"] = int(v)
        elif fnum == 4:
            has_nodes = bool(v)
    if has_nodes or nodes:
        m["nodes"] = nodes
    return m


def _enc_node_status(m: dict) -> bytes:
    out = b""
    if m.get("schema") is not None:
        out += _encode_bytes(1, _enc_schema(m["schema"]))
    out += _enc_avail(m.get("available") or {})
    return out


def _dec_node_status(data: bytes) -> dict:
    m: dict = {}
    avail: dict = {}
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            m["schema"] = _dec_schema(v)
        elif fnum == 15:
            _dec_avail_entry(v, avail)
    if avail:
        m["available"] = avail
    return m


def _enc_node_event(m: dict) -> bytes:
    out = _encode_string(1, m.get("event", ""))
    if m.get("node") is not None:
        out += _encode_bytes(2, _enc_node(m["node"]))
    if m.get("status") is not None:
        out += _encode_bytes(3, _enc_node_status(m["status"]))
    out += _encode_bool(4, bool(m.get("forwarded")))
    return out


def _dec_node_event(data: bytes) -> dict:
    m: dict = {"event": ""}
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            m["event"] = _field_str(v)
        elif fnum == 2:
            m["node"] = _dec_node(v)
        elif fnum == 3:
            m["status"] = _dec_node_status(v)
        elif fnum == 4 and v:
            m["forwarded"] = True
    return m


def _enc_node_state(m: dict) -> bytes:
    return _encode_string(1, m.get("id", "")) + _encode_string(
        2, m.get("state", "")
    )


def _dec_node_state(data: bytes) -> dict:
    m = {"id": "", "state": ""}
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            m["id"] = _field_str(v)
        elif fnum == 2:
            m["state"] = _field_str(v)
    return m


def _enc_resize_instruction(m: dict) -> bytes:
    out = _encode_uint64(1, int(m.get("job", 0)))
    if m.get("coordinator") is not None:
        out += _encode_bytes(2, _enc_node(m["coordinator"]))
    if m.get("schema") is not None:
        out += _encode_bytes(3, _enc_schema(m["schema"]))
    for src in m.get("sources") or []:
        out += _encode_bytes(4, _enc_source(src))
    out += _encode_string(5, str(m.get("node", "")))
    # Job epoch (ISSUE r9): completions must echo it or the coordinator
    # rejects them as stale — dropping it on the wire would reject EVERY
    # completion and wedge the job at its timeout.
    out += _encode_uint64(6, int(m.get("epoch") or 0))
    out += _enc_avail(m.get("available") or {})
    return out


def _dec_resize_instruction(data: bytes) -> dict:
    m: dict = {"job": 0, "epoch": 0, "sources": []}
    avail: dict = {}
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            m["job"] = int(v)
        elif fnum == 2:
            m["coordinator"] = _dec_node(v)
        elif fnum == 3:
            m["schema"] = _dec_schema(v)
        elif fnum == 4:
            m["sources"].append(_dec_source(v))
        elif fnum == 5:
            m["node"] = _field_str(v)
        elif fnum == 6:
            m["epoch"] = int(v)
        elif fnum == 15:
            _dec_avail_entry(v, avail)
    if avail:
        m["available"] = avail
    return m


def _enc_resize_complete(m: dict) -> bytes:
    out = _encode_uint64(1, int(m.get("job", 0)))
    out += _encode_string(2, m.get("node", ""))
    if m.get("error"):
        out += _encode_string(3, str(m["error"]))
    out += _encode_uint64(4, int(m.get("epoch") or 0))
    return out


def _dec_resize_complete(data: bytes) -> dict:
    m: dict = {"job": 0, "epoch": 0, "node": ""}
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            m["job"] = int(v)
        elif fnum == 2:
            m["node"] = _field_str(v)
        elif fnum == 3:
            m["error"] = _field_str(v)
        elif fnum == 4:
            m["epoch"] = int(v)
    return m


def _enc_set_coordinator(m: dict) -> bytes:
    return _encode_string(1, m.get("id", ""))


def _dec_set_coordinator(data: bytes) -> dict:
    m = {"id": ""}
    for fnum, _w, v in _iter_fields(data):
        if fnum == 1:
            m["id"] = _field_str(v)
    return m


def _enc_empty(m: dict) -> bytes:
    return b""


def _dec_empty(data: bytes) -> dict:
    return {}


# Registry: message type string -> (type byte, enc, dec). Type bytes
# mirror the reference's 1-byte prefixes (broadcast.go:55-122 ordering).
_REGISTRY = {
    "create-shard": (0x01, _enc_create_shard, _dec_create_shard),
    "delete-available-shard": (0x02, _enc_create_shard, _dec_create_shard),
    "cluster-status": (0x03, _enc_cluster_status, _dec_cluster_status),
    "node-status": (0x04, _enc_node_status, _dec_node_status),
    "node-event": (0x05, _enc_node_event, _dec_node_event),
    "node-state": (0x06, _enc_node_state, _dec_node_state),
    "resize-instruction": (0x07, _enc_resize_instruction, _dec_resize_instruction),
    "resize-complete": (0x08, _enc_resize_complete, _dec_resize_complete),
    "resize-abort": (0x09, _enc_empty, _dec_empty),
    "set-coordinator": (0x0A, _enc_set_coordinator, _dec_set_coordinator),
    "recalculate-caches": (0x0B, _enc_empty, _dec_empty),
}
_BY_BYTE = {tb: (typ, dec) for typ, (tb, _enc, dec) in _REGISTRY.items()}


class ProtoSerializer:
    """Typed binary for registered control messages; transparent JSON for
    anything else (forward compatibility). Unmarshal sniffs legacy JSON
    frames ('{' first byte) from older peers."""

    def marshal(self, msg: dict) -> bytes:
        entry = _REGISTRY.get(msg.get("type", ""))
        if entry is None:
            return json.dumps(msg).encode()
        type_byte, enc, _dec = entry
        return bytes((type_byte, WIRE_VERSION)) + enc(msg)

    def unmarshal(self, data: bytes) -> dict:
        if not data:
            raise ValueError("empty control message")
        if data[0] == 0x7B:  # '{' — legacy/fallback JSON frame
            return json.loads(data)
        if len(data) < 2:
            raise ValueError("truncated control message header")
        entry = _BY_BYTE.get(data[0])
        if entry is None or data[1] != WIRE_VERSION:
            # A NEWER peer sent a type/version we don't know. The receive
            # dispatch deliberately ignores unknown message types
            # (forward compatibility, reference server.go receiveMessage);
            # surface an ignorable message instead of 500ing the
            # /internal/cluster/message endpoint mid-rolling-upgrade.
            return {
                "type": f"unknown-wire-{data[0]:#04x}-v{data[1]}",
            }
        typ, dec = entry
        fields = dec(data[2:])
        fields["type"] = typ
        return fields


class JSONSerializer:
    """The debuggable fallback (still used by tests that inspect frames)."""

    def marshal(self, msg: dict) -> bytes:
        return json.dumps(msg).encode()

    def unmarshal(self, data: bytes) -> dict:
        return json.loads(data)
