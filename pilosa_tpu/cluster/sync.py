"""Cluster convergence: translate replication, anti-entropy, failure detection.

Three loops the reference runs as background monitors:

- **Key translation** (reference translate.go:35-70, holder.go:785-878):
  the coordinator is the translation primary. Non-coordinator stores wrap
  the local sqlite store in a ForwardingTranslateStore: key *writes*
  forward to the primary over RPC (so the same key gets the same id
  cluster-wide), and replicas tail the primary's entry log
  (entries_since) both on-demand (read miss) and from the sync daemon.
- **Anti-entropy** (reference holder.go:882-1101, server.go:514): the
  HolderSyncer periodically walks the schema and, for every fragment this
  node owns, diffs 100-row block checksums against each replica and
  merges differing blocks (union repair, fragment.go:1875). Attribute
  stores sync the same way over 100-id blocks. View names and available
  shards are pulled from peers first so a replica that missed a
  CREATE_SHARD broadcast converges too.
- **Failure detection** (reference gossip NotifyLeave + confirm-down
  retry, cluster.go:65-67): each node probes peers' /status; after
  CONFIRM_DOWN consecutive failures the peer is marked DOWN in the local
  topology (queries then skip it proactively instead of timing out per
  request) and the cluster degrades; a successful probe marks it READY.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from pilosa_tpu.cluster.client import ClientError
from pilosa_tpu.cluster.topology import NODE_STATE_DOWN, NODE_STATE_READY
from pilosa_tpu.utils.logger import NopLogger
from pilosa_tpu.utils.stats import global_stats


def _count_transition(node_id: str, to_state: str) -> None:
    """Membership state-transition counter (ISSUE r8): a flapping peer
    shows up as a climbing DOWN/READY pair on /metrics instead of only
    as interleaved log lines."""
    global_stats.with_tags(f"peer:{node_id}", f"to:{to_state}").count(
        "cluster_node_state_transitions_total"
    )

# Consecutive probe failures before a peer is declared down
# (the reference re-checks a leave event before acting, cluster.go:65).
CONFIRM_DOWN = 3


class ForwardingTranslateStore:
    """Wraps a node-local TranslateStore; assigns ids only on the primary.

    reference translate.go:35 (primary store) + http/translator.go (replica
    reader). The local store is a strict replica of the primary's log:
    entries are applied with their primary-assigned ids, so offsets never
    diverge.
    """

    def __init__(self, local, cluster, index: str, field: str = ""):
        self.local = local
        self.cluster = cluster
        self.index = index
        self.field = field

    # -- write path --------------------------------------------------------

    def translate_key(self, key: str, write: bool = True) -> Optional[int]:
        id_ = self.local.translate_key(key, write=False)
        if id_ is not None:
            return id_
        if self.cluster.is_coordinator():
            return self.local.translate_key(key, write=write)
        if not write:
            return None
        coord = self.cluster.coordinator()
        ids = self.cluster.client.translate_keys(coord, self.index, self.field, [key])
        # Catch the local replica up so the log has no gaps, then make sure
        # this entry landed even if the tail raced.
        self.sync_from_primary()
        self.local.apply_entries([(ids[0], key)])
        return ids[0]

    def translate_keys(self, keys: list[str], write: bool = True) -> list[Optional[int]]:
        """Bulk translation in ONE coordinator RPC + ONE log tail for all
        missing keys (VERDICT r2 weak #5: the per-key loop made a keyed
        import of 100k fresh keys 100k round trips; reference batches via
        TranslateKeysNode, http/client.go)."""
        out = self.local.translate_keys(keys, write=False)
        missing = [i for i, v in enumerate(out) if v is None]
        if not missing:
            return out
        if self.cluster.is_coordinator():
            if write:  # write=False misses are already known-absent
                filled = self.local.translate_keys(
                    [keys[i] for i in missing], write=True
                )
                for j, i in enumerate(missing):
                    out[i] = filled[j]
            return out
        if not write:
            return out
        coord = self.cluster.coordinator()
        ids = self.cluster.client.translate_keys(
            coord, self.index, self.field, [keys[i] for i in missing]
        )
        # Catch the local replica up so the log has no gaps, then make
        # sure these entries landed even if the tail raced.
        self.sync_from_primary()
        self.local.apply_entries(
            [(ids[j], keys[i]) for j, i in enumerate(missing)]
        )
        for j, i in enumerate(missing):
            out[i] = ids[j]
        return out

    # -- read path ---------------------------------------------------------

    def translate_id(self, id_: int) -> Optional[str]:
        k = self.local.translate_id(id_)
        if k is None and not self.cluster.is_coordinator():
            try:
                self.sync_from_primary()
            except ClientError:
                return None
            k = self.local.translate_id(id_)
        return k

    def translate_ids(self, ids: list[int]) -> list[Optional[str]]:
        """Bulk id -> key: one local bulk lookup; a replica with misses
        tails the primary ONCE and re-looks the misses up in bulk."""
        out = self.local.translate_ids(ids)
        missing = [i for i, v in enumerate(out) if v is None]
        if not missing or self.cluster.is_coordinator():
            return out
        try:
            self.sync_from_primary()
        except ClientError:
            return out
        filled = self.local.translate_ids([ids[i] for i in missing])
        for j, i in enumerate(missing):
            out[i] = filled[j]
        return out

    # -- replication -------------------------------------------------------

    def sync_from_primary(self) -> None:
        """Tail the primary's entry log (reference EntryReader stream)."""
        coord = self.cluster.coordinator()
        if coord is None or coord.id == self.cluster.local_node.id:
            return
        entries = self.cluster.client.translate_data(
            coord, self.index, self.field, self.local.max_id()
        )
        if entries:
            self.local.apply_entries([(int(s), k) for s, k in entries])

    # -- delegation --------------------------------------------------------

    def max_id(self) -> int:
        return self.local.max_id()

    def entries_since(self, seq: int):
        return self.local.entries_since(seq)

    def apply_entries(self, entries) -> None:
        self.local.apply_entries(entries)

    def close(self) -> None:
        self.local.close()


def wrap_translate_stores(cluster) -> None:
    """Install forwarding wrappers on every keyed store in the holder.
    Idempotent; called at attach and after any schema change."""
    holder = cluster.holder
    if holder is None:
        return
    for name in list(holder.indexes):
        idx = holder.index(name)
        if idx is None:
            continue
        if idx.translate_store is not None and not isinstance(
            idx.translate_store, ForwardingTranslateStore
        ):
            idx.translate_store = ForwardingTranslateStore(
                idx.translate_store, cluster, name
            )
        for fname in list(idx.fields):
            f = idx.field(fname)
            if f is not None and f.translate_store is not None and not isinstance(
                f.translate_store, ForwardingTranslateStore
            ):
                f.translate_store = ForwardingTranslateStore(
                    f.translate_store, cluster, name, fname
                )


class HolderSyncer:
    """Anti-entropy repair loop (reference holderSyncer holder.go:882)."""

    def __init__(self, cluster, logger=None):
        self.cluster = cluster
        self.log = logger or NopLogger()

    # -- one full pass -----------------------------------------------------

    def sync_holder(self) -> int:
        """Walk schema, diff checksums vs replicas, merge differing blocks.
        Returns the number of blocks repaired (reference SyncHolder
        holder.go:911).

        Observability (ISSUE r9 satellite): each pass counts
        anti_entropy_runs_total, times itself into the
        anti_entropy_run_seconds histogram, and stamps the
        anti_entropy_last_run_seconds gauge (monotonic clock, same base
        as the exported uptime: `uptime - value` is the run's age) — a
        stalled syncer on one node used to be invisible except as the
        absence of log lines."""
        holder = self.cluster.holder
        if holder is None:
            return 0
        t0 = time.monotonic()
        repaired = 0
        self._sync_schema()
        for index_name in list(holder.indexes):
            idx = holder.index(index_name)
            if idx is None:
                continue
            repaired += self._sync_attrs(index_name, None, idx.column_attr_store)
            for field_name in list(idx.fields):
                f = idx.field(field_name)
                if f is None:
                    continue
                repaired += self._sync_attrs(index_name, field_name, f.row_attr_store)
                self._pull_field_state(index_name, field_name, f)
                shards = f.available_shards().to_array().tolist()
                for view_name in list(f.views):
                    for shard in shards:
                        if not self.cluster.topology.owns_shard(
                            self.cluster.local_node.id, index_name, shard
                        ):
                            continue
                        if self._migration_in_flight(index_name, shard):
                            # A resize is mid-move on this shard: a
                            # repair sourced from a half-migrated peer
                            # fragment would ship a partial block as
                            # truth. Skip; the post-resize pass heals
                            # (ISSUE r15 satellite).
                            global_stats.with_tags("reason:resizing").count(
                                "anti_entropy_skipped_total"
                            )
                            continue
                        repaired += self._sync_fragment(
                            index_name, f, view_name, shard
                        )
        # Drain any control messages that failed to broadcast earlier.
        self.cluster.flush_pending_broadcasts()
        global_stats.count("anti_entropy_runs_total")
        global_stats.timing("anti_entropy_run_seconds", time.monotonic() - t0)
        global_stats.gauge("anti_entropy_last_run_seconds", time.monotonic())
        return repaired

    def _live_replicas(self, index: str, shard: int):
        local_id = self.cluster.local_node.id
        return [
            n
            for n in self.cluster.topology.shard_nodes(index, shard)
            if n.id != local_id and n.state != NODE_STATE_DOWN
        ]

    def _peers(self):
        local_id = self.cluster.local_node.id
        return [
            n
            for n in self.cluster.topology.nodes
            if n.id != local_id and n.state != NODE_STATE_DOWN
        ]

    def _sync_schema(self) -> None:
        """Pull peer schemas (repairs a missed DDL broadcast; reference
        syncs schema via NodeStatus gossip, holder.go:924)."""
        api = self.cluster.api
        if api is None:
            return
        for peer in self._peers():
            try:
                schema = self.cluster.client.schema(peer)
            except ClientError:
                continue
            try:
                api.apply_schema(schema)
            except Exception as e:
                self.log.printf("anti-entropy: apply schema from %s: %s", peer.id, e)
        wrap_translate_stores(self.cluster)

    def _pull_field_state(self, index: str, field_name: str, f) -> None:
        """Union peer view lists + available shards (repairs a missed
        CREATE_SHARD broadcast)."""
        for peer in self._peers():
            try:
                state = self.cluster.client.field_state(peer, index, field_name)
            except ClientError:
                continue
            for shard in state.get("availableShards", []):
                f.add_available_shard(int(shard))
            for view_name in state.get("views", []):
                f.create_view_if_not_exists(view_name)

    def _migration_in_flight(self, index: str, shard: int) -> bool:
        rz = self.cluster.resizer
        return rz is not None and rz.migration_in_flight(index, shard)

    def _sync_fragment(self, index: str, f, view_name: str, shard: int,
                       only_blocks=None) -> int:
        """Epoch-directed anti-entropy for one fragment (ISSUE r15
        tentpole 1). The wire ships per-block (checksum, epoch); a
        differing block resolves by the matrix:

          both epochs known, unequal  -> directed copy from the HIGHER
                                         epoch (clears included — this
                                         is what lets tombstones
                                         propagate); the lower side
                                         adopts the winner's epoch so
                                         replicas converge on both axes.
          both known, equal           -> union (two distinct writes can
                                         never share a stamp within one
                                         fragment, so an equal-epoch
                                         disagreement means the epoch
                                         plane cannot order them).
          either side unknown (0)     -> union (mixed-version peers,
                                         pre-upgrade data, crash-dropped
                                         sidecars) — NEVER a directed
                                         wipe of data nobody can date.

        `only_blocks` (read-repair plane) restricts the pass to the
        named block ids."""
        v = f.view(view_name)
        frag = v.fragment(shard) if v is not None else None
        repaired = 0
        for peer in self._live_replicas(index, shard):
            try:
                peer_blocks = self.cluster.client.fragment_blocks(
                    peer, index, f.name, view_name, shard
                )
            except ClientError:
                continue  # peer has no fragment (404) or is unreachable
            if not peer_blocks:
                continue
            local_blocks = (
                {b: (s, e) for b, s, e in frag.block_sums_epochs()}
                if frag is not None
                else {}
            )
            for block_id, checksum, peer_epoch in peer_blocks:
                if only_blocks is not None and block_id not in only_blocks:
                    continue
                local_sum, local_epoch = local_blocks.get(block_id, (0, 0))
                if local_sum == checksum:
                    continue
                directed = (
                    peer_epoch > 0
                    and local_epoch > 0
                    and peer_epoch != local_epoch
                )
                if directed and local_epoch > peer_epoch:
                    # Our block is newer: keep it. The peer's own pass
                    # (or its read-repair) pulls ours — counted so both
                    # heal directions are visible from one registry.
                    global_stats.with_tags("direction:local_wins").count(
                        "anti_entropy_directed_repairs_total"
                    )
                    continue
                try:
                    data, wire_epoch = self.cluster.client.block_data(
                        peer, index, f.name, view_name, shard, block_id
                    )
                except ClientError:
                    continue
                # The epoch that rode WITH the data supersedes the
                # snapshot's: a peer write between the two RPCs shipped
                # newer bytes, and stamping them with the older
                # snapshot epoch would diverge the epoch axis (epochs
                # only grow, so the higher-wins decision still holds).
                # wire_epoch 0 means the peer's block went
                # epoch-UNKNOWN in flight (a union merge landed there):
                # the directed/pull decision's basis is gone — zeroing
                # peer_epoch degrades this block to union.
                if wire_epoch > 0:
                    peer_epoch = wire_epoch
                else:
                    peer_epoch = 0
                    directed = False
                if frag is None:
                    frag = v.create_fragment_if_not_exists(shard) if v is not None else None
                    if frag is None:
                        frag = f.create_view_if_not_exists(
                            view_name
                        ).create_fragment_if_not_exists(shard)
                    local_blocks = {
                        b: (s, e) for b, s, e in frag.block_sums_epochs()
                    }
                # Pure pull into a block we have NO data and NO epoch
                # for: the union result IS the peer's block, so copying
                # it (epoch included) keeps replicas convergent on both
                # axes — and nothing local can be wiped, because there
                # is nothing local. Counted as the classic missed-write
                # block repair (kind=fragment), NOT as a directed
                # repair: the direction family is reserved for
                # epoch-ARBITRATED resolutions between two dated blocks.
                pull = (
                    not directed
                    and local_sum == 0
                    and local_epoch == 0
                    and peer_epoch > 0
                )
                if directed or pull:
                    # expected_local_epoch closes the snapshot-to-
                    # replace race: a client write landing between the
                    # (checksum, epoch) snapshot and this call minted a
                    # newer local epoch the decision never saw —
                    # replace_block skips (None) instead of wiping the
                    # acked write, and the next pass re-evaluates.
                    result = frag.replace_block(
                        block_id, data, peer_epoch,
                        expected_local_epoch=local_epoch,
                    )
                    if result is None:
                        global_stats.with_tags("reason:stale-epoch").count(
                            "anti_entropy_skipped_total"
                        )
                        continue
                    added, removed = result
                    if added or removed:
                        repaired += 1
                        if directed:
                            global_stats.with_tags(
                                "direction:remote_wins"
                            ).count("anti_entropy_directed_repairs_total")
                        else:
                            global_stats.with_tags("kind:fragment").count(
                                "anti_entropy_blocks_repaired_total"
                            )
                else:
                    added, _ = frag.merge_block(block_id, data)
                    if added:
                        repaired += 1
                        global_stats.with_tags("kind:fragment").count(
                            "anti_entropy_blocks_repaired_total"
                        )
        return repaired

    def sync_fragment_targeted(self, index: str, field: str, view_name: str,
                               shard: int, blocks=None) -> int:
        """One fragment's epoch-directed repair, outside the full pass —
        the read-repair queue's unit of work. Skips (0) while the shard
        is mid-migration, exactly like the daemon pass."""
        from pilosa_tpu.utils.deadline import Deadline, current_deadline, deadline_scope

        holder = self.cluster.holder
        idx = holder.index(index) if holder is not None else None
        f = idx.field(field) if idx is not None else None
        if f is None:
            return 0
        # Ownership guard, same as the daemon pass: a read-repair RPC
        # can land MINUTES after the hedge observation (bounded queue x
        # per-probe budget), by which time a resize may have moved the
        # shard off this node — repairing here would recreate and
        # repopulate a fragment cleanup already removed.
        if not self.cluster.topology.owns_shard(
            self.cluster.local_node.id, index, shard
        ):
            global_stats.with_tags("reason:not-owner").count(
                "anti_entropy_skipped_total"
            )
            return 0
        if self._migration_in_flight(index, shard):
            global_stats.with_tags("reason:resizing").count(
                "anti_entropy_skipped_total"
            )
            return 0
        # Budget the repair's peer RPCs (deadline-scope rule): the
        # /internal/fragment/repair handler and the divergence worker
        # both land here; an inherited request budget is honored, a
        # bare call gets its own bound so a stalled replica can't pin
        # the caller.
        d = current_deadline()
        with deadline_scope(d if d is not None else Deadline(30.0)):
            return self._sync_fragment(
                index, f, view_name, shard,
                only_blocks=set(blocks) if blocks else None,
            )

    def _sync_attrs(self, index: str, field_name: Optional[str], store) -> int:
        """100-id block diff + merge (reference holder.go:975-1067)."""
        if store is None:
            return 0
        repaired = 0
        for peer in self._peers():
            try:
                peer_blocks = self.cluster.client.attr_blocks(peer, index, field_name)
            except ClientError:
                continue
            local_blocks = dict(store.blocks())
            for block_id, checksum in peer_blocks:
                if local_blocks.get(block_id) == checksum:
                    continue
                try:
                    data = self.cluster.client.attr_block_data(
                        peer, index, field_name, block_id
                    )
                except ClientError:
                    continue
                for id_, attrs in data.items():
                    if attrs:
                        store.set_attrs(int(id_), attrs)
                        repaired += 1
                        global_stats.with_tags("kind:attr").count(
                            "anti_entropy_blocks_repaired_total"
                        )
        return repaired

    def _sync_translation(self) -> None:
        """Replica-side tail of the primary's key logs."""
        holder = self.cluster.holder
        if holder is None or self.cluster.is_coordinator():
            return
        for name in list(holder.indexes):
            idx = holder.index(name)
            if idx is None:
                continue
            stores = [idx.translate_store] + [
                idx.field(fn).translate_store
                for fn in list(idx.fields)
                if idx.field(fn) is not None
            ]
            for st in stores:
                if isinstance(st, ForwardingTranslateStore):
                    try:
                        st.sync_from_primary()
                    except ClientError:
                        pass


class SyncDaemon:
    """Background thread running anti-entropy + translate tailing on an
    interval (reference monitorAntiEntropy server.go:514)."""

    def __init__(self, cluster, interval: float = 600.0, logger=None):
        self.cluster = cluster
        self.interval = interval
        self.syncer = HolderSyncer(cluster, logger)
        self.log = logger or NopLogger()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SyncDaemon":
        from pilosa_tpu.utils.threads import spawn

        self._thread = spawn("sync-daemon", self._run)
        return self

    def _run(self) -> None:
        # ±25% jitter per cycle (ISSUE r9 satellite): a fleet restarted
        # together would otherwise run synchronized cluster-wide checksum
        # storms at every interval, forever — the phases decorrelate
        # within a few cycles instead.
        from pilosa_tpu.utils.deadline import Deadline, deadline_scope

        while not self._stop.wait(self.interval * (0.75 + 0.5 * random.random())):
            try:
                # Budget the whole pass (deadline-scope rule): every
                # peer RPC below bounds its socket timeout by the
                # remainder and rides X-Pilosa-Deadline, so a stalled
                # peer can pin the syncer for at most one pass — the
                # next jittered cycle starts from a clean budget. The
                # 60 s floor keeps test-sized intervals from starving
                # an honest pass.
                with deadline_scope(Deadline(max(self.interval, 60.0))):
                    n = self.syncer.sync_holder()
                    self.syncer._sync_translation()
                if n:
                    self.log.printf("anti-entropy: repaired %d blocks", n)
            except Exception as e:
                self.log.printf("anti-entropy error: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class FailureDetector:
    """Static-topology liveness probe (the gossip-membership replacement;
    TPU pods have fixed peers, SURVEY.md §2.2 gossip row)."""

    def __init__(self, cluster, interval: float = 1.0, confirm_down: int = CONFIRM_DOWN,
                 logger=None):
        self.cluster = cluster
        # Backref for the asymmetric-partition guard: disseminated DOWN
        # claims consult our probe history (cluster.receive_message).
        cluster.failure_detector = self
        self.interval = interval
        self.confirm_down = confirm_down
        self.log = logger or NopLogger()
        self._fails: dict[str, int] = {}
        # Guards the confirm counters: the probe loop's increments race
        # the message handler's vote_down RMWs on the same key
        # (shared-state rule), and a lost increment delays a legitimate
        # DOWN confirmation by a whole probe sweep.
        self._fails_lock = threading.Lock()
        # (peer id, subject id) -> last state that peer reported for the
        # subject. Peer-view DOWN observations vote only on the
        # TRANSITION to DOWN (SWIM-style), not on every repeated stale
        # snapshot — re-counting an unchanged report each sweep would
        # flap a recovered node back DOWN (code review r4).
        self._peer_reports: dict[tuple[str, str], str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def vote_down(self, node_id: str) -> bool:
        """A peer's disseminated DOWN claim counts as ONE vote on our
        confirm counter — same SWIM discipline as _merge_peer_view,
        never an outright overwrite (a single transient local probe
        failure plus one broadcast must not mark a reachable node DOWN;
        code review r5). No vote at all while our probes succeed.
        Returns True when the accumulated evidence reaches
        confirm_down (the caller then applies the DOWN)."""
        with self._fails_lock:
            if self._fails.get(node_id, 0) <= 0:
                return False
            self._fails[node_id] += 1
            return self._fails[node_id] >= self.confirm_down

    def probe_once(self) -> None:
        topo = self.cluster.topology
        local_id = self.cluster.local_node.id
        for node in list(topo.nodes):
            if node.id == local_id:
                continue
            try:
                st = self.cluster.client.status(node)
                ok = True
            except ClientError:
                st = None
                ok = False
            if ok:
                with self._fails_lock:
                    self._fails[node.id] = 0
                if node.state == NODE_STATE_DOWN:
                    node.state = NODE_STATE_READY
                    self.log.printf("node %s is back up", node.id)
                    _count_transition(node.id, NODE_STATE_READY)
                    self._disseminate(node.id, NODE_STATE_READY)
                    self._heal_returning_node(node)
                self._merge_peer_view(node, st)
            else:
                global_stats.with_tags(f"peer:{node.id}").count(
                    "cluster_probe_failures_total"
                )
                with self._fails_lock:
                    self._fails[node.id] = self._fails.get(node.id, 0) + 1
                    confirmed = self._fails[node.id] >= self.confirm_down
                if confirmed and node.state != NODE_STATE_DOWN:
                    node.state = NODE_STATE_DOWN
                    self.log.printf("node %s marked down", node.id)
                    _count_transition(node.id, NODE_STATE_DOWN)
                    self._disseminate(node.id, NODE_STATE_DOWN)
        # Cluster state follows membership (reference determineClusterState
        # cluster.go:571): any down node + replication -> DEGRADED.
        from pilosa_tpu.cluster.topology import STATE_DEGRADED, STATE_NORMAL

        any_down = any(n.state == NODE_STATE_DOWN for n in topo.nodes)
        state = self.cluster.state()
        if any_down and topo.replica_n > 1 and state == STATE_NORMAL:
            self.cluster.set_state(STATE_DEGRADED)
        elif not any_down and state == STATE_DEGRADED:
            self.cluster.set_state(STATE_NORMAL)
        self._maybe_promote_coordinator()

    # -- piggybacked membership exchange (VERDICT r3 #5) -------------------

    def _merge_peer_view(self, peer, st: Optional[dict]) -> None:
        """Each probe response carries the peer's full node view — merge
        it (the gossip LocalState/MergeRemoteState NodeStatus exchange,
        reference gossip.go:321-362, piggybacked on the existing probe
        loop instead of a separate transport):

        - A peer-observed DOWN for a third node counts as ONE vote on our
          confirm-down counter — but only on the peer's TRANSITION to
          reporting DOWN, and only while our own probes of that node are
          also failing (votes accelerate a DOWN we are witnessing; they
          never originate one for a node we can reach). k probing peers
          then converge in ~confirm_down/k rounds instead of each
          independently burning confirm_down probes.
        - A coordinator flag on a live peer view replaces ours when OUR
          recorded coordinator is dead or missing — how a node that
          missed MSG_SET_COORDINATOR (e.g. was partitioned during the
          failover) catches up without any coordinator involvement.
        """
        if not st:
            return
        # View-epoch piggyback on the probe plane (ISSUE r15 tentpole
        # 3): every probe response refreshes the peer's epoch report, so
        # the clustered result cache's staleness window for writes that
        # never route through the coordinator is bounded by the probe
        # interval.
        epochs = st.get("indexEpochs")
        if isinstance(epochs, dict):
            self.cluster.fold_peer_epochs(
                {"node": peer.id, "boot": st.get("indexEpochsBoot"),
                 "indexes": epochs}
            )
        local = {n.id: n for n in self.cluster.topology.nodes}
        local_id = self.cluster.local_node.id
        for nd in st.get("nodes", []):
            nid = nd.get("id")
            target = local.get(nid)
            if target is None or nid in (local_id, peer.id):
                continue
            state = nd.get("state")
            prev = self._peer_reports.get((peer.id, nid))
            self._peer_reports[(peer.id, nid)] = state
            if (
                state == NODE_STATE_DOWN
                and prev != NODE_STATE_DOWN  # transition, not a stale echo
                and target.state != NODE_STATE_DOWN
                # vote_down is the one locked counter path: "we are
                # failing it too" + increment + confirm, atomically.
                and self.vote_down(nid)
            ):
                target.state = NODE_STATE_DOWN
                self.log.printf(
                    "node %s marked down (peer %s's observation)",
                    nid, peer.id,
                )
                _count_transition(nid, NODE_STATE_DOWN)
                self._disseminate(nid, NODE_STATE_DOWN)
        peer_coord = next(
            (nd.get("id") for nd in st.get("nodes", []) if nd.get("isCoordinator")),
            None,
        )
        if peer_coord is not None:
            ours = next(
                (n for n in self.cluster.topology.nodes if n.is_coordinator), None
            )
            cand = local.get(peer_coord)
            if (
                cand is not None
                and cand.state != NODE_STATE_DOWN
                and (ours is None or ours.state == NODE_STATE_DOWN)
                and (ours is None or ours.id != peer_coord)
            ):
                was_coordinator = self.cluster.local_node.is_coordinator
                for n in self.cluster.topology.nodes:
                    n.is_coordinator = n.id == peer_coord
                self.cluster.local_node.is_coordinator = local_id == peer_coord
                self.cluster.persist_topology()
                self.log.printf(
                    "adopted coordinator %s from peer %s's view", peer_coord, peer.id
                )
                if (
                    self.cluster.local_node.is_coordinator
                    and not was_coordinator
                    and self.cluster.resizer is not None
                ):
                    self.cluster.resizer.on_promoted()
        # A peer frozen in RESIZING on a job this (coordinator) node
        # doesn't own reports the orphaned job in its /status; adopt and
        # abort it so the follower unfreezes before its own lease fires
        # (ISSUE r9 tentpole 1).
        from pilosa_tpu.cluster.topology import STATE_RESIZING

        rz_info = st.get("resize")
        if (
            rz_info
            and st.get("state") == STATE_RESIZING
            and self.cluster.is_coordinator()
            and self.cluster.resizer is not None
        ):
            self.cluster.resizer.observe_follower(rz_info)

    def _heal_returning_node(self, node) -> None:
        """A node that comes back READY missed every broadcast while it
        was down; if WE are the coordinator, re-send it the coordinator
        identity + current membership so a returning OLD coordinator
        stops believing it still leads (reference re-sends ClusterStatus
        on nodeJoin, cluster.go:2121)."""
        if not self.cluster.is_coordinator():
            return
        from pilosa_tpu.cluster import broadcast as bc

        try:
            self.cluster.broadcaster.send_to(
                node,
                bc.Message.make(
                    bc.MSG_SET_COORDINATOR, id=self.cluster.local_node.id
                ),
            )
            self.cluster.broadcaster.send_to(
                node,
                bc.Message.make(
                    bc.MSG_CLUSTER_STATUS,
                    state=self.cluster.state(),
                    nodes=self.cluster.nodes_json(),
                    replicaN=self.cluster.topology.replica_n,
                ),
            )
        except Exception as e:  # noqa: BLE001 — next probe retries
            self.log.printf("heal status to %s failed: %s", node.id, e)

    def _maybe_promote_coordinator(self) -> None:
        """Coordinator failover (VERDICT r3 #5; reference
        api.go:1193-1261 SetCoordinator made automatic): when the
        recorded coordinator is confirmed DOWN, the lowest-id READY node
        deterministically promotes itself and broadcasts
        MSG_SET_COORDINATOR — every live node computes the same
        successor, so there is no election traffic; laggards converge
        via the broadcast or the piggybacked view merge above. The
        translate primary and join/resize handling follow coordinator()
        dynamically, so they move with the flag."""
        topo = self.cluster.topology
        coord = next((n for n in topo.nodes if n.is_coordinator), None)
        if coord is None or coord.state != NODE_STATE_DOWN:
            return
        ready = [n for n in topo.nodes if n.state != NODE_STATE_DOWN]
        if not ready:
            return
        successor = min(ready, key=lambda n: n.id)
        if successor.id != self.cluster.local_node.id:
            return  # the successor promotes itself; we adopt its broadcast
        self.log.printf(
            "coordinator %s is down: promoting self (%s)", coord.id, successor.id
        )
        global_stats.count("cluster_coordinator_promotions_total")
        from pilosa_tpu.cluster import broadcast as bc

        for n in topo.nodes:
            n.is_coordinator = n.id == successor.id
        self.cluster.local_node.is_coordinator = True
        self.cluster.persist_topology()
        self.cluster.broadcaster.send_async(
            bc.Message.make(bc.MSG_SET_COORDINATOR, id=successor.id)
        )
        # A promotion mid-resize adopts (and aborts) the dead
        # coordinator's orphaned job so followers unfreeze without
        # waiting out their leases (ISSUE r9 tentpole 1).
        if self.cluster.resizer is not None:
            self.cluster.resizer.on_promoted()

    def _disseminate(self, node_id: str, state: str) -> None:
        """Share the observed transition over the broadcast bus so every
        node's view converges within one probe interval instead of each
        independently burning confirm_down probes (reference shares
        membership via gossip events, gossip.go:364-443). Best-effort:
        probes keep running either way."""
        from pilosa_tpu.cluster import broadcast as bc

        try:
            self.cluster.broadcaster.send_async(
                bc.Message.make(bc.MSG_NODE_STATE, id=node_id, state=state)
            )
        except Exception as e:  # noqa: BLE001 — liveness must not die
            self.log.printf("node-state broadcast failed: %s", e)

    def start(self) -> "FailureDetector":
        from pilosa_tpu.utils.threads import spawn

        self._thread = spawn("failure-detector", self._run)
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.probe_once()
            except Exception as e:
                self.log.printf("failure detector error: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
