"""Cluster runtime: scatter-gather mapReduce, write replication, control
messages (reference cluster.go:186 + executor.go:2419-2613).

A Cluster binds a local node into a Topology and installs two seams on
the Executor:

- ``mapper`` — the distributed mapReduce. Shards are grouped by owning
  node (reference shardsByNode executor.go:2440); local shards run
  through the backend in-process (on TPU that is one batched XLA program
  over the mesh), remote groups become one QueryNode HTTP call each with
  shards pinned and remote=true (reference remoteExec :2419). Responses
  stream-reduce as they arrive; a failed node is filtered out and its
  shards re-split across remaining replicas (reference :2497-2507).
- ``router`` — write replication. Set/Clear apply on every replica of
  the target shard (reference executeSetBitField :2096-2135); attribute
  writes fan to all nodes (attr stores are fully replicated).
"""

from __future__ import annotations

import itertools
import queue
import random
import time
import threading

import numpy as np
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.broadcast import HTTPBroadcaster, Message, NopBroadcaster
from pilosa_tpu.cluster.client import ClientError, InternalClient
from pilosa_tpu.cluster.topology import (
    NODE_STATE_DOWN,
    NODE_STATE_READY,
    Node,
    STATE_DEGRADED,
    STATE_NORMAL,
    Topology,
)
from pilosa_tpu.core.cache import Pair
from pilosa_tpu.core.row import Row
from pilosa_tpu.exec.result import (
    FieldRow,
    GroupCount,
    PairField,
    RowIDs,
    ValCount,
)
from pilosa_tpu.utils.threads import spawn


class ShardUnavailableError(Exception):
    """No live node owns a shard (reference errShardUnavailable)."""


@dataclass
class _MapResponse:
    node: Node
    shards: list[int]
    result: Any = None
    err: Optional[Exception] = None
    # Leg identity for hedged reads: the gather loop accepts a response
    # only while its shard set is still unreduced, so a hedge's loser is
    # discarded instead of double-reduced.
    leg: int = 0
    attempt: int = 1


class Cluster:
    def __init__(
        self,
        local_node: Node,
        topology: Topology,
        holder=None,
        client: Optional[InternalClient] = None,
        use_broadcast: bool = True,
        state: str = STATE_NORMAL,
    ):
        self.local_node = local_node
        self.topology = topology
        self.holder = holder
        self.client = client or InternalClient()
        self._state = state
        self._state_lock = threading.RLock()
        self.executor = None
        self.broadcaster = (
            HTTPBroadcaster(self, self.client) if use_broadcast else NopBroadcaster()
        )
        # Seams the resize/anti-entropy layers hook (set by attach_* below).
        self.resizer = None
        self.api = None
        self.logger = None
        # Control messages that failed to broadcast; retried by the sync
        # daemon (ADVICE r1: a dropped DDL/shard broadcast must not be
        # silently lost). Entries are [msg, attempts, next_due]: a
        # message that keeps failing backs off exponentially (capped,
        # jittered) instead of re-hammering dead peers every sync pass.
        self._pending_msgs: list[list] = []
        self._pending_lock = threading.Lock()
        # Schema-repair throttle per (node, index): a query naming a
        # genuinely nonexistent field must not trigger a schema push +
        # duplicate remote execution on every query (ADVICE r2). Entries
        # expire after repair_retry_interval (a permanent throttle would
        # disable the NotFound repair the moment one bad-field query came
        # through); cleared on membership change or successful repair.
        self._repair_attempted: dict[tuple[str, str], float] = {}
        # Guards the throttle's check-then-arm: scatter-gather worker
        # threads race each other (and the membership-change clear) on
        # the same (node, index) key, and an unguarded get-then-set
        # would let N concurrent queries all start repair pushes
        # (shared-state rule).
        self._repair_lock = threading.Lock()
        self.repair_retry_interval: float = 30.0
        # Hedged shard reads (ISSUE r9 tentpole 3): a remote leg that
        # hasn't answered after this many seconds is re-launched at the
        # next live replica, first result wins. 0 disables. The CLI wires
        # the `hedge-delay` config; the default here is off so direct
        # Cluster constructions (tests, embedders) opt in explicitly.
        self.hedge_delay: float = 0.0
        # Monotonic leg ids for the hedged gather (shared across
        # concurrent map_shards calls; uniqueness is all that matters).
        self._leg_ids = itertools.count(1)
        # Path of the persisted-topology file (ISSUE r9 tentpole 3):
        # when set (the CLI points it at <data-dir>/.topology), every
        # durable membership change — CLUSTER_STATUS node lists,
        # coordinator moves — rewrites it atomically so a restarting
        # node rejoins with its same identity and a full-cluster restart
        # reconverges without operator re-seeding.
        self.topology_file: Optional[str] = None
        self._topology_file_lock = threading.Lock()
        # Read-path divergence monitor (cluster/consistency.py, ISSUE
        # r15 tentpole 2): when wired, a hedge race's two answers are
        # handed over for a background checksum diff + targeted repair.
        self.divergence = None
        # Per-peer view-epoch map (ISSUE r15 tentpole 3): node id ->
        # {index -> {field -> {"structure": int, "views": {view: gen}}}}
        # folded from X-Pilosa-View-Epochs piggybacks on internal RPC
        # responses (remote query legs, replica writes) and from the
        # failure detector's /status probes. The clustered coordinator's
        # result cache keys fan-out answers on this map — see
        # rescache_peer_epochs below.
        self._peer_epochs: dict[str, dict] = {}
        self._peer_epochs_lock = threading.Lock()
        # Shard-set -> covering-peer memo for the provider's hot path
        # (one topology walk per distinct shard tuple, not per lookup);
        # invalidated wholesale on any membership change. Both the memo
        # and the generation it keys on share the peer-epoch lock.
        self._owners_memo: dict = {}
        self._topo_gen = 0

    def persist_topology(self) -> None:
        """Best-effort atomic rewrite of the topology file; a failed
        persist is logged (the live cluster keeps working — the file
        only matters at the NEXT boot). Serialized: during a failover
        the broadcast handler and the failure detector both persist, and
        two writers sharing the one tmp file would interleave into torn
        JSON — losing the identity the file exists to preserve."""
        if not self.topology_file:
            return
        from pilosa_tpu.cluster.topology import save_topology

        epoch = 0
        if self.resizer is not None:
            # The resize epoch survives coordinator restarts through the
            # same file, so a rebooted coordinator's fresh jobs can never
            # reuse a dead job's (job, epoch) identity.
            epoch = self.resizer._epoch
        try:
            with self._topology_file_lock:
                save_topology(
                    self.topology_file, self.topology, self.local_node.id,
                    resize_epoch=epoch,
                )
        except OSError as e:
            self._log("topology persist to %s failed: %s", self.topology_file, e)

    # -- wiring ------------------------------------------------------------

    def attach(self, executor, api=None) -> None:
        """Install the cluster seams on an executor + holder + API."""
        self.executor = executor
        executor.mapper = self.map_shards
        executor.router = self
        self.api = api
        if self.holder is not None:
            self.holder.broadcast_shard = self._on_local_new_shard
        # Peer view-epoch piggybacks fold into this node's epoch map
        # (ISSUE r15 tentpole 3) — and when a result cache is wired,
        # the provider below is what lets a CLUSTERED coordinator
        # consult it: fan-out answers key on the merged (local + peer)
        # epoch vector instead of being uncacheable.
        self.client.on_peer_epochs = self.fold_peer_epochs
        if getattr(executor, "rescache", None) is not None:
            executor.rescache.peer_epochs_provider = self.rescache_peer_epochs
        # Keyed translation routes through the coordinator primary.
        from pilosa_tpu.cluster.sync import wrap_translate_stores

        wrap_translate_stores(self)

    def attach_resizer(self, logger=None):
        """Install the resize state machine (cluster/resize.py)."""
        from pilosa_tpu.cluster.resize import Resizer

        return Resizer(self, logger or self.logger)

    def _log(self, fmt: str, *args) -> None:
        if self.logger is not None:
            self.logger.printf(fmt, *args)

    # -- identity / state --------------------------------------------------

    @property
    def node_id(self) -> str:
        return self.local_node.id

    def state(self) -> str:
        with self._state_lock:
            return self._state

    def set_state(self, state: str) -> None:
        with self._state_lock:
            changed = state != self._state
            self._state = state
        if changed:
            # NORMAL<->DEGRADED<->RESIZING transitions on /metrics: a
            # wedged resize shows up as a RESIZING transition with no
            # matching NORMAL, next to a flatlined migration gauge.
            from pilosa_tpu.utils.stats import global_stats

            global_stats.with_tags(f"state:{state}").count(
                "cluster_state_transitions_total"
            )

    def is_coordinator(self) -> bool:
        return self.local_node.is_coordinator

    def coordinator(self) -> Optional[Node]:
        for n in self.topology.nodes:
            if n.is_coordinator:
                return n
        return self.topology.nodes[0] if self.topology.nodes else None

    def node_status(self) -> dict:
        """This node's schema + per-field available shards — the
        NodeStatus a joiner ships so the cluster learns what data it
        already holds (reference gossip LocalState/MergeRemoteState,
        gossip.go:321-362)."""
        status: dict = {"schema": {"indexes": []}, "available": {}}
        if self.holder is not None:
            status["schema"] = {"indexes": self.holder.schema()}
            for iname in list(self.holder.indexes):
                idx = self.holder.index(iname)
                if idx is None:
                    continue
                fields = {}
                for fname in list(idx.fields):
                    f = idx.field(fname)
                    if f is not None:
                        shards = f.available_shards().to_array().tolist()
                        if shards:
                            fields[fname] = [int(s) for s in shards]
                if fields:
                    status["available"][iname] = fields
        return status

    def merge_node_status(self, status: dict) -> None:
        """Apply a peer's NodeStatus: schema union + available shards
        (reference mergeRemoteState → holder schema/availableShards)."""
        if not status:
            return
        if self.api is not None and status.get("schema"):
            self.api.apply_schema(status["schema"])
            from pilosa_tpu.cluster.sync import wrap_translate_stores

            wrap_translate_stores(self)
        from pilosa_tpu.roaring import Bitmap

        for iname, fields in status.get("available", {}).items():
            idx = self.holder.index(iname) if self.holder else None
            if idx is None:
                continue
            for fname, shards in fields.items():
                f = idx.field(fname)
                if f is not None and shards:
                    # Bulk union + ONE persist (field.go:274 analog) —
                    # per-shard add_available_shard would rewrite the
                    # bitmap file once per shard inside the message
                    # handler.
                    bm = Bitmap()
                    bm.add_many(
                        np.array([int(s) for s in shards], dtype=np.uint64),
                        log=False,
                    )
                    f.merge_remote_available_shards(bm)

    def join_cluster(
        self, coordinator_uri, timeout: float = 60.0, announce_every: float = 2.0
    ) -> bool:
        """Dynamic membership (VERDICT r2 #6; reference gossip join →
        listenForJoins cluster.go:1063-1141): announce this node to the
        coordinator with a JOIN node event carrying our NodeStatus, then
        wait for the resize machinery to deliver schema + fragments and
        flip the topology (MSG_CLUSTER_STATUS) to include us. Re-announces
        until membership lands or the timeout expires. Returns True once
        this node is a member of a multi-node topology."""
        msg = Message.make(
            bc.MSG_NODE_EVENT,
            event=bc.EVENT_JOIN,
            node=self.local_node.to_json(),
            status=self.node_status(),
        )
        deadline = time.monotonic() + timeout
        # Failed announces retry on capped jittered exponential backoff
        # (ISSUE r9 satellite — the fixed interval hammered a coordinator
        # that was mid-restart exactly when it could least absorb it):
        # 0.25 s doubling to a cap, each interval jittered 0.5-1.5x so a
        # fleet of rebooting joiners doesn't retry in lockstep. A
        # successful send re-asserts at the steady announce_every pace
        # (membership may still take a resize to land) and resets the
        # backoff.
        backoff = 0.25
        cap = max(4 * announce_every, 8.0)
        next_announce = 0.0
        attempt = 0
        while time.monotonic() < deadline:
            member = any(
                n.id == self.local_node.id for n in self.topology.nodes
            ) and len(self.topology.nodes) > 1
            if member and self.state() == STATE_NORMAL:
                self._log(
                    "joined cluster: %d nodes (%d announce attempts)",
                    len(self.topology.nodes), attempt,
                )
                return True
            if time.monotonic() >= next_announce:
                attempt += 1
                try:
                    # Through the broadcaster so the announce gets the
                    # per-peer JSON wire fallback too — a JSON-only
                    # coordinator mid-rolling-upgrade must still accept
                    # a new build's join (code review r4).
                    self.broadcaster.send_to(coordinator_uri, msg)
                    backoff = 0.25
                    interval = announce_every
                except Exception as e:  # noqa: BLE001 — keep re-announcing
                    interval = backoff
                    backoff = min(backoff * 2, cap)
                    self._log(
                        "join announce attempt %d failed (retry in ~%.2fs): %s",
                        attempt, interval, e,
                    )
                next_announce = time.monotonic() + interval * (
                    0.5 + random.random()
                )
            time.sleep(0.05)
        self._log("join timed out after %d announce attempts", attempt)
        return False

    def nodes_json(self) -> list[dict]:
        return [n.to_json() for n in self.topology.nodes]

    def shard_nodes_json(self, index: str, shard: int) -> list[dict]:
        return [n.to_json() for n in self.topology.shard_nodes(index, shard)]

    # -- peer view-epoch plane (ISSUE r15 tentpole 3) ----------------------

    @staticmethod
    def _merge_report(stored: dict, new: dict) -> dict:
        """Per-view monotone merge of two same-incarnation reports: the
        new snapshot is the base (additions adopted), but no individual
        stored generation may regress — the report walk on the peer is
        lock-free, so a report can be TORN (one view read pre-mint,
        another post), and a per-report max guard alone would let a
        torn report with a high max fold a regressed view generation
        back over a newer one, re-validating a cache entry a
        synchronous write invalidation already killed. Generations are
        per-process monotone, so per-view max is exact. (A view deleted
        within one incarnation lingers as a ghost at its last
        generation until the peer restarts — it can never change again,
        so an equality-compared signature through it is stable, never
        stale.)"""
        out = dict(new)
        for fname, old_f in stored.items():
            new_f = out.get(fname)
            if not isinstance(old_f, dict):
                continue
            if not isinstance(new_f, dict):
                out[fname] = old_f
                continue
            merged = dict(new_f)
            old_s, new_s = old_f.get("structure"), new_f.get("structure")
            if isinstance(old_s, int) and (
                not isinstance(new_s, int) or old_s > new_s
            ):
                merged["structure"] = old_s
            old_v = old_f.get("views")
            if isinstance(old_v, dict):
                new_v = merged.get("views")
                mv = dict(new_v) if isinstance(new_v, dict) else {}
                for vname, g in old_v.items():
                    cur = mv.get(vname)
                    if isinstance(g, int) and (
                        not isinstance(cur, int) or g > cur
                    ):
                        mv[vname] = g
                merged["views"] = mv
            out[fname] = merged
        return out

    @staticmethod
    def _report_max(report: dict) -> int:
        """Newest generation anywhere in one index's epoch report — the
        report's ORDER among reports from the same peer, because a
        peer's generations all come from one monotonic per-process
        counter."""
        top = 0
        for f in report.values():
            if not isinstance(f, dict):
                continue
            s = f.get("structure")
            if isinstance(s, int) and s > top:
                top = s
            views = f.get("views")
            if isinstance(views, dict):
                for g in views.values():
                    if isinstance(g, int) and g > top:
                        top = g
        return top

    def fold_peer_epochs(self, payload: dict) -> None:
        """Fold one piggybacked epoch report ({"node": id, "indexes":
        {index: {field: {"structure": int, "views": {view: gen}}}}})
        into the per-peer map. Reports are whole-index snapshots —
        generations are minted from one monotonic per-process counter
        (wall-seeded, so a restarted peer can never repeat a value) and
        the cache compares them for EQUALITY only. Folds can arrive OUT
        OF ORDER (a slow read leg's response races a later write's), so
        a report only replaces the stored one when its newest
        generation is >= the stored report's: an older snapshot folding
        back over a newer one would re-validate a cache entry that a
        synchronous write invalidation already killed. (A deletion-only
        change can lower the max — that stale entry lasts only until
        the peer's next mint, and a deleted field can't serve anyway.)"""
        node_id = payload.get("node")
        indexes = payload.get("indexes")
        boot = payload.get("boot")
        if not node_id or not isinstance(indexes, dict):
            return
        if node_id == self.local_node.id:
            return  # our own loopback report: the local vector covers it
        # Entries store (boot, report_max, report). Same-incarnation
        # folds MERGE per-view monotone (see _merge_report: torn
        # reports must never regress an individual generation; merge is
        # commutative, so arrival order stops mattering entirely). A
        # boot change — the peer restarted; its post-clock-step counter
        # may mint below its previous life — or an unknown boot
        # (mixed-version peers) replaces wholesale: the reborn process
        # is fresh truth, deletions included. The incoming report's max
        # walk happens out here, unlocked; the merge walk runs under
        # the lock but only per FOLD (one per RPC response), never on
        # the cache-lookup path.
        prepared = [
            (index, self._report_max(report), report)
            for index, report in indexes.items()
            if isinstance(report, dict)
        ]
        if not prepared:
            return
        with self._peer_epochs_lock:
            per_node = self._peer_epochs.setdefault(node_id, {})
            for index, mx, report in prepared:
                stored = per_node.get(index)
                if (
                    stored is not None
                    and boot is not None
                    and stored[0] == boot
                ):
                    report = self._merge_report(stored[2], report)
                    mx = max(mx, stored[1])
                per_node[index] = (boot, mx, report)

    def _covering_peers(self, index: str, shards_t: tuple) -> frozenset:
        """Node ids (excluding this node) owning any replica of any
        covered shard — every node whose writes could change a fan-out
        answer over this shard set. Memoized per (index, shard tuple,
        membership generation)."""
        with self._peer_epochs_lock:
            key = (index, shards_t, self._topo_gen)
            got = self._owners_memo.get(key)
        if got is not None:
            return got
        local_id = self.local_node.id
        out = set()
        for s in shards_t:
            for n in self.topology.shard_nodes(index, s):
                if n.id != local_id:
                    out.add(n.id)
        got = frozenset(out)
        with self._peer_epochs_lock:
            if len(self._owners_memo) > 64:
                self._owners_memo.clear()
            self._owners_memo[key] = got
        return got

    def rescache_peer_epochs(self, index: str, field_names, shards_t: tuple):
        """The result cache's peer-epoch provider: a tuple signature of
        every covering peer's last-reported epochs for the covered
        fields, or None when any covering peer's state is unknown
        (nothing piggybacked yet — the first fan-out populates the map,
        so only the answer AFTER it becomes cacheable). () means the
        shard set is covered locally and no peer vector is needed.

        Freshness contract (docs/administration.md "Result caching"):
        the map advances on every internal RPC response from a peer —
        coordinator-routed writes invalidate synchronously — and on the
        failure detector's ~1 s /status probes, which bound the
        staleness window for writes entering via other nodes."""
        peers = self._covering_peers(index, shards_t)
        if not peers:
            return ()
        # Lock held only for the ref grabs: folds REPLACE a peer's
        # report wholesale (never mutate in place), so the references
        # are stable snapshots and the O(fields x views) signature walk
        # + sorts run outside the lock every RPC piggyback fold and
        # every other cache lookup contends for.
        reports = []
        with self._peer_epochs_lock:
            for nid in sorted(peers):
                entry = self._peer_epochs.get(nid, {}).get(index)
                per_index = entry[2] if entry else None
                if not per_index:
                    return None
                reports.append((nid, per_index))
        out = []
        for nid, per_index in reports:
            for fname in field_names:
                frep = per_index.get(fname)
                if not isinstance(frep, dict):
                    return None
                out.append((nid, fname, -1, frep.get("structure")))
                views = frep.get("views") or {}
                for vname in sorted(views):
                    out.append((nid, fname, vname, views[vname]))
        return tuple(out)

    # -- mapReduce (reference executor.go:2460-2613) -----------------------

    def _routable_nodes(self, index, shards):
        """Scatter-gather candidates: DOWN nodes are skipped up front, and
        so are peers whose circuit breaker is open (ISSUE r9 tentpole 2)
        — both route traffic straight to replicas instead of eating a
        timeout. Each filter is dropped again if it would orphan a shard:
        availability beats the optimization."""
        from pilosa_tpu.cluster.client import peer_label
        from pilosa_tpu.cluster.topology import NODE_STATE_DOWN

        live = [n for n in self.topology.nodes if n.state != NODE_STATE_DOWN]
        if not live:
            live = list(self.topology.nodes)
        breakers = getattr(self.client, "breakers", None)
        if breakers is not None:
            unblocked = [
                n
                for n in live
                if n.id == self.local_node.id
                or not breakers.is_blocked(peer_label(n))
            ]
            if unblocked and unblocked != live:
                try:
                    self._shards_by_node(unblocked, index, shards)
                    return unblocked
                except ShardUnavailableError:
                    pass  # a blocked peer is some shard's only owner
        return live

    def map_shards(self, index, shards, c, map_fn, reduce_fn, opt):
        from pilosa_tpu.cluster.client import count_rpc_retry, peer_label
        from pilosa_tpu.utils.deadline import current_deadline
        from pilosa_tpu.utils.stats import global_stats

        nodes = self._routable_nodes(index, shards)
        ch: "queue.Queue[_MapResponse]" = queue.Queue()
        # The caller's active span (executor.Execute / the HTTP span) is
        # captured HERE because the mapper legs run on fresh threads whose
        # thread-local span stacks are empty — without handing the parent
        # over, the client would find no active span and the trace would
        # die at the node boundary (ISSUE r8 tentpole 1). The active
        # Deadline crosses the same thread boundary the same way.
        from pilosa_tpu.utils.tracing import global_tracer

        parent_span = global_tracer.active_span()
        deadline = current_deadline()

        # Hedged gather state: every launched leg is tracked until its
        # shard set is reduced. `needed` is the set of shards still
        # awaiting exactly one reduction; a response is accepted only if
        # its whole shard set is still needed, so a hedge's loser — or a
        # straggler whose shards a hedge already covered — is discarded
        # instead of double-reduced.
        inflight: dict[int, dict] = {}
        needed: set[int] = set(shards)
        hedged: set[int] = set()  # parent leg ids with a hedge in flight
        # Parents no longer hedge-eligible: already hedged, or hedging
        # was tried and no live alternate owns their shards. Tracked
        # separately from `hedged` so an unhedgeable straggler stops
        # driving the gather wait to zero (busy-poll) without ever being
        # scored as a hedge win/loss.
        hedge_done: set[int] = set()
        scored: set[int] = set()  # hedged parents already counted won/lost

        def launch(target_nodes, shard_list, attempt=1, parent=None):
            groups = self._shards_by_node(target_nodes, index, shard_list)
            for node, node_shards in groups.values():
                leg = next(self._leg_ids)
                inflight[leg] = {
                    "node": node,
                    "shards": node_shards,
                    "t0": time.monotonic(),
                    "attempt": attempt,
                    "parent": parent if parent is not None else leg,
                }
                spawn(
                    "cluster-map",
                    self._map_node,
                    args=(ch, leg, attempt, node, node_shards, index, c,
                          map_fn, reduce_fn, opt, parent_span, deadline),
                )

        launch(nodes, list(shards))

        result = None
        got_any = False
        # The gather wait is budget-derived (ISSUE r9: was a flat
        # client.timeout + 30): the deadline governs when one is active,
        # and the old cap stays as the no-deadline backstop — every
        # remote leg's socket timeout already ends below it.
        hard_cap = time.monotonic() + self.client.timeout + 30
        while needed:
            if deadline is not None:
                deadline.check("gather")
            now = time.monotonic()
            wait = hard_cap - now
            if deadline is not None:
                wait = min(wait, deadline.remaining())
            if self.hedge_delay > 0:
                for rec in inflight.values():
                    if (
                        rec["attempt"] == 1
                        and rec["parent"] not in hedge_done
                        and rec["node"].id != self.local_node.id
                    ):
                        wait = min(wait, rec["t0"] + self.hedge_delay - now)
            if now >= hard_cap:
                # A worker hung past the client timeout; surface as a
                # routable 5xx instead of an unhandled traceback (ADVICE r1).
                raise ShardUnavailableError(
                    f"query timed out waiting for shard results ({index})"
                ) from None
            try:
                resp = ch.get(timeout=max(wait, 0.001))
            except queue.Empty:
                self._maybe_hedge(
                    launch, inflight, needed, hedged, hedge_done, nodes
                )
                continue
            rec = inflight.pop(resp.leg, {"attempt": resp.attempt,
                                          "parent": resp.leg})
            if resp.err is not None:
                # Re-split the failed leg's still-needed shards across
                # the remaining replicas (reference :2497-2507). Shards a
                # hedge already reduced need no retry — and shards a
                # SIBLING attempt of the same parent still has in flight
                # (the primary of a failed hedge, or the hedge of a
                # failed primary) are already covered: re-splitting them
                # would duplicate the dispatch, and raising would abort a
                # query the sibling may still answer. Only shards no
                # sibling covers re-split (or raise).
                covered: set[int] = set()
                for r in inflight.values():
                    if r["parent"] == rec["parent"]:
                        covered.update(r["shards"])
                still = [
                    s for s in resp.shards if s in needed and s not in covered
                ]
                if not still:
                    continue
                count_rpc_retry(peer_label(resp.node), "query_node")
                nodes = [n for n in nodes if n.id != resp.node.id]
                try:
                    launch(nodes, still, attempt=rec["attempt"],
                           parent=rec["parent"])
                except ShardUnavailableError:
                    raise resp.err
                continue
            if not set(resp.shards) <= needed:
                # A sibling attempt already reduced part of this shard
                # set: the loser of a hedge race. Any shard of it still
                # needed is covered by an in-flight sibling (hedges cover
                # the straggler's full shard set), so dropping the whole
                # response is safe and the only way not to double-count.
                continue
            needed.difference_update(resp.shards)
            if rec["parent"] in hedged and rec["parent"] not in scored:
                scored.add(rec["parent"])
                won = "hedge" if rec["attempt"] > 1 else "primary"
                global_stats.with_tags(f"won:{won}").count(
                    "hedged_requests_total"
                )
                # The hedge RACED two replicas over one shard set — a
                # free consistency probe (ISSUE r15 tentpole 2). The
                # winner's response plus its still-inflight sibling
                # identify both replicas; the checksum diff runs on the
                # monitor's thread, never here (one bounded-queue
                # append). Observed at scoring time because the loser's
                # answer usually lands AFTER this gather returns.
                if self.divergence is not None:
                    from pilosa_tpu.cluster.consistency import call_fields

                    for r in inflight.values():
                        if (
                            r["parent"] == rec["parent"]
                            and r["node"].id != resp.node.id
                        ):
                            common = set(r["shards"]) & set(resp.shards)
                            if common:
                                # Scoped to the fields the hedged read
                                # touched: the probe diffs what the
                                # race witnessed, the sweep covers the
                                # rest of the schema.
                                self.divergence.observe(
                                    index, common, resp.node.id,
                                    r["node"].id, fields=call_fields(c),
                                )
            if got_any:
                result = reduce_fn(result, resp.result)
            else:
                result = resp.result
                got_any = True
        return result

    def _maybe_hedge(self, launch, inflight, needed, hedged, hedge_done,
                     nodes) -> None:
        """Re-launch every straggler remote leg's shards at the next live
        replica (first result wins; see the needed-set accounting above).
        A leg with no alternate owner for its shards is marked done (so
        the gather stops waking up for it) and left to its socket
        timeout — the error path re-splits what it can. Eligibility is
        keyed by PARENT id: a re-split leg carries its original parent,
        and hedging it twice would storm duplicate legs."""
        if self.hedge_delay <= 0:
            return
        now = time.monotonic()
        for rec in list(inflight.values()):
            if (
                rec["attempt"] != 1
                or rec["parent"] in hedge_done
                or rec["node"].id == self.local_node.id
                or now - rec["t0"] < self.hedge_delay
                or not any(s in needed for s in rec["shards"])
            ):
                continue
            alternates = [n for n in nodes if n.id != rec["node"].id]
            try:
                launch(alternates, [s for s in rec["shards"] if s in needed],
                       attempt=2, parent=rec["parent"])
            except ShardUnavailableError:
                hedge_done.add(rec["parent"])  # nowhere to hedge: stop waking
                continue
            hedge_done.add(rec["parent"])
            hedged.add(rec["parent"])

    def _shards_by_node(self, nodes: Sequence[Node], index: str, shards: Sequence[int]):
        m: dict[str, tuple[Node, list[int]]] = {}
        live = {n.id for n in nodes}
        for shard in shards:
            owner = None
            for n in self.topology.shard_nodes(index, shard):
                if n.id in live:
                    owner = n
                    break
            if owner is None:
                raise ShardUnavailableError(f"shard {shard} unavailable")
            m.setdefault(owner.id, (owner, []))[1].append(shard)
        return m

    def _map_node(self, ch, leg, attempt, node, node_shards, index, c,
                  map_fn, reduce_fn, opt, parent_span=None,
                  deadline=None) -> None:
        # Re-establish the trace context on this worker thread: one child
        # span per scatter-gather leg, tagged with the target node, so a
        # slow leg is directly visible in the assembled cross-node tree
        # (and remote legs inject X-Trace-Id via the client). The
        # caller's Deadline is re-activated the same way so the client
        # bounds and propagates the remaining budget.
        from pilosa_tpu.utils.deadline import deadline_scope

        span = None
        if parent_span is not None:
            from pilosa_tpu.utils.tracing import global_tracer

            span = global_tracer.start_span(
                "cluster.mapShards", headers=parent_span.inject_headers()
            )
            # targetNode, NOT node: the node tag means "where this span
            # RAN" to the trace assembler (origin attribution + the
            # cross-node clock-skew check), and this span runs on the
            # coordinator regardless of which peer the leg targets.
            span.set_tag("targetNode", node.id)
            span.set_tag("shards", len(node_shards))
            if attempt > 1:
                span.set_tag("hedge", attempt)
        resp = _MapResponse(node=node, shards=node_shards, leg=leg,
                            attempt=attempt)
        try:
            with deadline_scope(deadline):
                if node.id == self.local_node.id:
                    result = None
                    first = True
                    for shard in node_shards:
                        v = map_fn(shard)
                        result = v if first else reduce_fn(result, v)
                        first = False
                    resp.result = result
                else:
                    resp.result = self._remote_exec(
                        node, index, c, node_shards,
                        bypass=getattr(opt, "cache_bypass", False),
                    )
        except Exception as e:  # transport or peer error -> retried upstream
            resp.err = e
            if span is not None:
                span.set_tag("error", str(e)[:200])
        finally:
            if span is not None:
                span.finish()
        ch.put(resp)

    def _remote_exec(self, node, index, c, shards, bypass=False):
        try:
            out = self.client.query_node(
                node, index, c.to_string(), shards=shards, remote=True,
                bypass=bypass,
            )
        except ClientError as e:
            # A peer that missed a DDL broadcast answers code=not-found:
            # push it the schema and retry once (ADVICE r1: pull schema on
            # NotFound instead of failing until anti-entropy). At most one
            # repair attempt per (node, index): a genuinely nonexistent
            # field otherwise costs a schema push + duplicate remote
            # execution on EVERY query (ADVICE r2). The structured error
            # code replaces substring matching (ADVICE r2 #4): an
            # unrelated error merely containing 'not found' can no longer
            # trigger a repair storm.
            repair_key = (node.id, index)
            if getattr(e, "code", "") != "not-found":
                raise
            with self._repair_lock:
                last = self._repair_attempted.get(repair_key)
                throttled = (
                    last is not None
                    and time.monotonic() - last < self.repair_retry_interval
                )
                if throttled:
                    raise
                # Armed inside the lock: concurrent legs hitting the
                # same missing schema run ONE repair, not one each.
                self._repair_attempted[repair_key] = time.monotonic()
            self._push_state_to(node, index)
            from pilosa_tpu.cluster.client import count_rpc_retry, peer_label

            count_rpc_retry(peer_label(node), "query_node")
            out = self.client.query_node(
                node, index, c.to_string(), shards=shards, remote=True,
                bypass=bypass,
            )
            # The retry succeeded: the peer genuinely lacked schema and is
            # now repaired. Forget the attempt so a FUTURE missed DDL on
            # the same index can be repaired too; only the
            # genuinely-nonexistent-field case stays throttled.
            with self._repair_lock:
                self._repair_attempted.pop(repair_key, None)
        results = out.get("results", [])
        raw = results[0] if results else None
        return decode_result(c, raw)

    def _push_state_to(self, node, index: str) -> None:
        """Repair one peer's schema + available shards inline."""
        if self.holder is None:
            return
        self.broadcaster.send_to(
            node, Message.make(bc.MSG_NODE_STATUS, schema={"indexes": self.holder.schema()})
        )
        idx = self.holder.index(index)
        if idx is None:
            return
        for fname in list(idx.fields):
            f = idx.field(fname)
            if f is None:
                continue
            for shard in f.available_shards().to_array().tolist():
                self.broadcaster.send_to(
                    node,
                    Message.make(
                        bc.MSG_CREATE_SHARD, index=index, field=fname, shard=int(shard)
                    ),
                )

    # -- write replication (reference executor.go:2072-2141) ---------------

    def _parallel_peer_writes(self, peers: Sequence[Node], index: str, pql: str,
                              shards: Optional[dict[str, list[int]]] = None) -> list[Any]:
        """Fire one remote-exec per peer concurrently; first error raised.
        shards maps node id -> pinned shard list (None = unpinned)."""
        results: list[Any] = [None] * len(peers)
        errs: list[Exception] = []
        lock = threading.Lock()
        # Same cross-thread trace handoff as map_shards: replica writes
        # run on fresh threads, so the parent span — and the active
        # Deadline — are captured here.
        from pilosa_tpu.utils.deadline import current_deadline, deadline_scope
        from pilosa_tpu.utils.tracing import global_tracer

        parent_span = global_tracer.active_span()
        deadline = current_deadline()

        def send(i, node):
            span = None
            if parent_span is not None:
                span = global_tracer.start_span(
                    "cluster.replicaWrite",
                    headers=parent_span.inject_headers(),
                )
                span.set_tag("targetNode", node.id)
            try:
                with deadline_scope(deadline):
                    out = self.client.query_node(
                        node, index, pql,
                        shards=shards.get(node.id) if shards else None,
                        remote=True,
                    )
                rs = out.get("results", [])
                results[i] = rs[0] if rs else None
            except Exception as e:
                with lock:
                    errs.append(e)
            finally:
                if span is not None:
                    span.finish()

        threads = [
            spawn("cluster-broadcast", send, args=(i, n), start=False)
            for i, n in enumerate(peers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
        return results

    def _peer_unwritable(self, n: Node) -> bool:
        """A replica the write path skips: DOWN, or circuit-broken (an
        open breaker is treated exactly like DOWN — the write routes to
        the remaining replicas and anti-entropy repairs the peer when its
        breaker closes)."""
        if n.state == NODE_STATE_DOWN:
            return True
        breakers = getattr(self.client, "breakers", None)
        if breakers is None:
            return False
        from pilosa_tpu.cluster.client import peer_label

        return breakers.is_blocked(peer_label(n))

    def _no_live_replica(self, index: str, shard: int) -> ClientError:
        """All replicas of a shard are unwritable: fail LOUDLY — a
        silently dropped write is unrepairable (no replica ever held it).
        Counted so an operator sees the rejection rate, not just client
        complaints."""
        from pilosa_tpu.utils.stats import global_stats

        global_stats.with_tags(f"index:{index}").count(
            "write_replica_unavailable_total"
        )
        return ClientError(
            f"every replica of shard {shard} is down; write not applied",
            code="replicas-unavailable",
        )

    def route_write(self, index: str, c, shard: int, local_fn: Callable[[], Any]):
        """Apply a single-shard write on every replica; OR the changed
        flags (reference executeSetBitField: ret = changed on any node)."""
        replicas = self.topology.shard_nodes(index, shard)
        # DOWN or circuit-broken replicas are skipped (reads already skip
        # them in map_shards); anti-entropy delivers the write when they
        # return — but ONLY if at least one live replica takes it now.
        peers = [
            n
            for n in replicas
            if n.id != self.local_node.id and not self._peer_unwritable(n)
        ]
        local_is_replica = any(n.id == self.local_node.id for n in replicas)
        if replicas and not peers and not local_is_replica:
            raise self._no_live_replica(index, shard)
        ret = None
        if local_is_replica:
            ret = local_fn()
        for r in self._parallel_peer_writes(peers, index, c.to_string()):
            if ret is None:
                ret = r
            elif isinstance(r, bool):
                ret = bool(ret) or r
        return ret

    def route_write_shards(self, index: str, c, shards: Sequence[int],
                           local_fn: Callable[[int], Any]):
        """Multi-shard write (ClearRow/Store) applied on EVERY replica of
        every shard: local shards via local_fn, remote groups as one
        pinned remote-exec per node. The reference routes these through
        plain mapReduce (one owner per shard, executor.go:1871-1953),
        which silently diverges replicas until anti-entropy — replicating
        here keeps replicas consistent at write time."""
        by_node: dict[str, tuple[Node, list[int]]] = {}
        for shard in shards:
            reps = self.topology.shard_nodes(index, shard)
            if reps and all(
                self._peer_unwritable(n) and n.id != self.local_node.id
                for n in reps
            ):
                # No live replica for THIS shard: fail loudly — a
                # silently skipped shard write is unrepairable.
                raise self._no_live_replica(index, shard)
            for node in reps:
                by_node.setdefault(node.id, (node, []))[1].append(shard)
        ret = None
        local = by_node.pop(self.local_node.id, None)
        if local is not None:
            for shard in local[1]:
                r = local_fn(shard)
                ret = r if ret is None else (bool(ret) or bool(r))
        peers = [
            node for node, _ in by_node.values()
            if not self._peer_unwritable(node)
        ]
        pinned = {node.id: ss for node, ss in by_node.values()}
        for r in self._parallel_peer_writes(peers, index, c.to_string(), pinned):
            if ret is None:
                ret = r
            elif isinstance(r, bool):
                ret = bool(ret) or r
        return ret

    def fan_out_all(self, index: str, c, local_fn: Callable[[], Any]):
        """Apply on every node (attr writes; stores fully replicated,
        reference executeSetRowAttrs remote fan-out)."""
        ret = local_fn()
        peers = [n for n in self.topology.nodes if n.id != self.local_node.id]
        self._parallel_peer_writes(peers, index, c.to_string())
        return ret

    # -- schema / shard propagation ----------------------------------------

    def broadcast_schema(self) -> None:
        """Push the full schema to peers after a local DDL (the reference
        broadcasts per-op messages, broadcast.go:57-79; a full-schema sync
        is simpler and idempotent — receivers apply_schema)."""
        if self.holder is None:
            return
        # Local DDL may have created keyed stores: route them first.
        from pilosa_tpu.cluster.sync import wrap_translate_stores

        wrap_translate_stores(self)
        msg = Message.make(bc.MSG_NODE_STATUS, schema={"indexes": self.holder.schema()})
        self._send_or_queue(msg)

    def _on_local_new_shard(self, index: str, field: str, shard: int) -> None:
        # Sync so a query routed through any node right after a write sees
        # the new shard in its fan-out set; down peers are repaired by
        # anti-entropy later.
        self._send_or_queue(
            Message.make(bc.MSG_CREATE_SHARD, index=index, field=field, shard=shard)
        )

    def _send_or_queue(self, msg: Message) -> None:
        """Sync broadcast; failures are logged and queued for retry by the
        sync daemon instead of dropped (ADVICE r1 medium)."""
        try:
            self.broadcaster.send_sync(msg)
        except RuntimeError as e:
            self._log("broadcast failed (queued for retry): %s", e)
            self._queue_pending(msg, attempts=1)

    def _queue_pending(self, msg: Message, attempts: int) -> None:
        """First failure retries at the very next flush; repeated
        failures back off exponentially (jittered 0.5-1.5x, capped at
        60 s) so a long-dead peer costs one send per cap interval, not
        one per queued message per sync pass."""
        if attempts <= 1:
            due = 0.0
        else:
            base = min(0.5 * (2 ** (attempts - 1)), 60.0)
            due = time.monotonic() + base * (0.5 + random.random())
        with self._pending_lock:
            self._pending_msgs.append([msg, attempts, due])

    def flush_pending_broadcasts(self) -> None:
        now = time.monotonic()
        with self._pending_lock:
            due = [e for e in self._pending_msgs if e[2] <= now]
            self._pending_msgs = [e for e in self._pending_msgs if e[2] > now]
        for msg, attempts, _ in due:
            try:
                self.broadcaster.send_sync(msg)
            except RuntimeError as e:
                self._log(
                    "broadcast retry attempt %d failed (backing off): %s",
                    attempts + 1, e,
                )
                self._queue_pending(msg, attempts + 1)

    # -- message receive (reference server.go receiveMessage :569) ---------

    def receive_message(self, payload: bytes) -> None:
        self.apply_message(Message.from_bytes(payload))

    def apply_message(self, msg: Message) -> None:
        typ = msg.get("type")
        if typ == bc.MSG_CREATE_SHARD:
            idx = self.holder.index(msg["index"]) if self.holder else None
            f = idx.field(msg["field"]) if idx else None
            if f is not None:
                f.add_available_shard(int(msg["shard"]))
        elif typ == bc.MSG_DELETE_AVAILABLE_SHARD:
            idx = self.holder.index(msg["index"]) if self.holder else None
            f = idx.field(msg["field"]) if idx else None
            if f is not None:
                f.remove_available_shard(int(msg["shard"]))
        elif typ == bc.MSG_NODE_STATUS:
            # schema + (optionally) per-field available shards — the
            # rejoin path ships both so a restarted node immediately fans
            # queries out over every shard (code review r4: schema alone
            # left available_shards empty until anti-entropy, silently
            # undercounting queries routed through the rejoined node).
            self.merge_node_status(
                {k: msg[k] for k in ("schema", "available") if k in msg}
            )
        elif typ == bc.MSG_CLUSTER_STATUS:
            self.set_state(msg.get("state", self.state()))
            if "replicaN" in msg:
                # lint: allow-shared-state(membership swap: each store is a GIL-atomic publish and readers tolerate one stale view until the next CLUSTER_STATUS frame)
                self.topology.replica_n = int(msg["replicaN"])
                with self._peer_epochs_lock:
                    self._topo_gen += 1  # replica fan changes ownership
            if "nodes" in msg:
                new_nodes = sorted(
                    (Node.from_json(d) for d in msg["nodes"]), key=lambda n: n.id
                )
                self.topology.nodes = new_nodes
                with self._repair_lock:
                    self._repair_attempted.clear()
                # Membership moved: shard ownership may have too — the
                # covering-peer memo keys on this generation, and a
                # departed peer's epoch report must not keep validating
                # cache entries it can no longer witness.
                live = {n.id for n in new_nodes}
                with self._peer_epochs_lock:
                    self._topo_gen += 1
                    for nid in list(self._peer_epochs):
                        if nid not in live:
                            del self._peer_epochs[nid]
                # Membership changed: re-negotiate control-plane wire
                # format per peer (a replaced node may speak binary now).
                self.broadcaster.reset_wire_negotiation()
                # Keep the local node's identity object in sync (it may
                # have just become or stopped being a member/coordinator).
                mine = next((n for n in new_nodes if n.id == self.local_node.id), None)
                if mine is not None:
                    # lint: allow-shared-state(identity swap: atomic publish of the replacement Node object; readers key off the stable node id)
                    self.local_node = mine
                # Membership is durable state: persist so a restart
                # rejoins with the same identity (ISSUE r9 tentpole 3).
                self.persist_topology()
            if self.resizer is not None:
                from pilosa_tpu.cluster.topology import STATE_RESIZING

                if msg.get("state") == STATE_RESIZING:
                    # The freeze arms the follower's rollback lease: a
                    # coordinator that dies right after freezing must not
                    # strand this node in RESIZING forever.
                    self.resizer.renew_lease(msg)
                elif "state" in msg:
                    self.resizer.cancel_lease()
            if msg.get("state") == STATE_NORMAL and self.resizer is not None:
                self.resizer.clean_holder()
        elif typ == bc.MSG_RESIZE_HEARTBEAT:
            if self.resizer is not None:
                self.resizer.renew_lease(msg)
        elif typ == bc.MSG_RECALCULATE_CACHES:
            if self.api is not None:
                self.api.recalculate_caches()
        elif typ == bc.MSG_RESIZE_INSTRUCTION:
            if self.resizer is not None:
                # Follow asynchronously: the instruction fetches fragments
                # from peers, which must not block the coordinator's
                # broadcast round-trip.
                spawn(
                    "resize-follower",
                    self.resizer.follow_instruction, args=(msg,),
                )
        elif typ == bc.MSG_RESIZE_COMPLETE:
            if self.resizer is not None:
                self.resizer.mark_complete(msg)
        elif typ == bc.MSG_RESIZE_ABORT:
            if self.resizer is not None:
                # local=True: a received abort is applied, never echoed —
                # two nodes both holding the coordinator flag during a
                # failover window would otherwise ping-pong it forever.
                self.resizer.abort(local=True)
        elif typ == bc.MSG_NODE_EVENT:
            self._handle_node_event(msg)
        elif typ == bc.MSG_NODE_STATE:
            # Disseminated liveness (VERDICT r2 weak #10: each node used
            # to discover DOWN peers only by its own probes, so views
            # could disagree indefinitely; reference shares this via
            # gossip events, gossip.go:364-443).
            nid, state = msg.get("id"), msg.get("state")
            target = self.topology.node_by_id(nid)
            if target is not None and nid != self.local_node.id and state in (
                NODE_STATE_READY,
                NODE_STATE_DOWN,
            ):
                # Asymmetric-partition guard (SWIM-style, r5): a peer's
                # DOWN claim is a VOTE against our own probe history,
                # never an overwrite — an unconditional overwrite let
                # one one-sided partition flap the whole cluster
                # (claimer marks DOWN and broadcasts; a healthy
                # receiver overwrites, then its own next probe flips it
                # READY and re-broadcasts, forever). Symmetric failures
                # (the node is really dead) still converge fast: every
                # receiver's probes are failing too, so the vote tops
                # up their confirm counter.
                fd = getattr(self, "failure_detector", None)
                if (
                    state == NODE_STATE_DOWN
                    and fd is not None
                    and not fd.vote_down(nid)
                ):
                    return
                target.state = state
        elif typ == bc.MSG_SET_COORDINATOR:
            new_id = msg.get("id")
            was_coordinator = self.local_node.is_coordinator
            for n in self.topology.nodes:
                n.is_coordinator = n.id == new_id
            self.local_node.is_coordinator = self.local_node.id == new_id
            self.persist_topology()
            if (
                self.local_node.is_coordinator
                and not was_coordinator
                and self.resizer is not None
            ):
                # A promotion mid-resize adopts (and aborts) the dead
                # coordinator's orphaned job (ISSUE r9 tentpole 1).
                self.resizer.on_promoted()
        # unknown types ignored (forward compatibility)

    def _handle_node_event(self, msg: Message) -> None:
        event = msg.get("event")
        node = Node.from_json(msg["node"]) if "node" in msg else None
        if node is None:
            return
        if event == bc.EVENT_JOIN:
            if self.is_coordinator() and self.resizer is not None:
                # NodeStatus ships with the announce: a restarting node's
                # schema/shard inventory merges BEFORE the resize job
                # diffs fragment sources, so its data counts as present.
                self.merge_node_status(msg.get("status") or {})
                self.resizer.handle_join(node)
            elif not msg.get("forwarded"):
                # Announce landed on a member that isn't the coordinator
                # (e.g. coordinatorship moved after the operator noted
                # the URI): forward once instead of silently dropping.
                coord = self.coordinator()
                if coord is not None and coord.id != self.local_node.id:
                    fwd = Message(msg)
                    fwd["forwarded"] = True
                    try:
                        self.broadcaster.send_to(coord, fwd)
                    except Exception as e:  # noqa: BLE001 — joiner retries
                        self._log("join forward to coordinator failed: %s", e)
        elif event == bc.EVENT_LEAVE:
            existing = self.topology.node_by_id(node.id)
            if existing is not None:
                existing.state = "DOWN"
            # Degraded until repaired/resized (reference determineClusterState
            # cluster.go:571: missing node + replicas -> DEGRADED).
            if self.topology.replica_n > 1:
                self.set_state(STATE_DEGRADED)


# ---------------------------------------------------------------------------
# remote result decoding (reference QueryResponse protobuf -> result types)
# ---------------------------------------------------------------------------

_ROW_CALLS = frozenset(
    ("Row", "Range", "Union", "Intersect", "Difference", "Xor", "Not", "Shift", "All")
)


def decode_result(c, raw: Any) -> Any:
    """JSON result from a peer -> the executor's native result type, so the
    coordinator's reduce functions work unchanged."""
    name = c.name
    if name == "Count":
        return int(raw or 0)
    if name in ("Sum", "Min", "Max"):
        raw = raw or {}
        return ValCount(val=int(raw.get("value", 0)), count=int(raw.get("count", 0)))
    if name in ("MinRow", "MaxRow"):
        raw = raw or {}
        field_name = str(c.args.get("_field") or c.args.get("field") or "")
        return PairField(
            Pair(id=int(raw.get("id", 0)), count=int(raw.get("count", 0))),
            field_name,
        )
    if name == "TopN":
        # Shard-level merge type is a plain pair list (add_pairs); the
        # coordinator wraps the final PairsField.
        return [
            Pair(id=int(p.get("id", 0)), count=int(p.get("count", 0)),
                 key=p.get("key", ""))
            for p in (raw or [])
        ]
    if name == "Rows":
        raw = raw or {}
        out = RowIDs(int(r) for r in raw.get("rows", []))
        if "keys" in raw:
            out.keys = list(raw["keys"])
        return out
    if name == "GroupBy":
        out_groups = []
        for g in raw or []:
            frs = [
                FieldRow(
                    field=fr["field"],
                    row_id=int(fr.get("rowID", 0)),
                    row_key=fr.get("rowKey", ""),
                )
                for fr in g.get("group", [])
            ]
            out_groups.append(GroupCount(frs, int(g.get("count", 0))))
        return out_groups
    if name in ("Set", "Clear", "Store", "ClearRow"):
        return bool(raw)
    if name in ("SetRowAttrs", "SetColumnAttrs"):
        return None
    if name == "Options":
        return decode_result(c.children[0], raw) if c.children else raw
    if name in _ROW_CALLS:
        raw = raw or {}
        row = Row(int(v) for v in raw.get("columns", []))
        if raw.get("attrs"):
            row.attrs = raw["attrs"]
        return row
    return raw
